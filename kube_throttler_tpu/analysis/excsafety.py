"""Checker 7: exception safety — resources that leak on the error path.

The PR 6 review's lease-elector bug: ``os.open`` the lease file, then
``flock`` it — and when ``flock`` raised anything unexpected, the fd
leaked, silently holding the flock for the process lifetime and wedging
every future acquire on the host. The class is "resource acquired, then
fallible work, then ownership transfer — with no protection in between".
Three rules:

1. **Explicit ``.acquire()``.** A lock acquired outside ``with`` must be
   released in a ``finally`` — a function containing ``X.acquire()``
   without any ``finally`` that calls ``.release()`` keeps the lock on
   every exception path. (Lock-wrapper internals — functions named
   ``acquire``/``release``/``__enter__``/``__exit__``/``_acquire_restore``
   /``_release_save`` and the ``utils/lockorder`` module itself — are
   the implementation, not users, and are exempt.)

2. **Fd/socket/tempfile lifetime.** A call to ``open`` / ``os.open`` /
   ``socket.socket`` / ``socket.socketpair`` / ``tempfile.mkstemp`` /
   ``tempfile.NamedTemporaryFile`` / ``.makefile()`` assigned to a local
   name must be *secured* — stored on ``self``/a container, returned, or
   consumed by ``os.fdopen`` — before any other fallible call runs, OR
   every fallible call in between must sit in a ``try`` whose handlers or
   ``finally`` close the resource. Release-only-on-success shapes are
   flagged at the first unprotected fallible call between creation and
   the close; a resource never closed and never escaping is flagged as
   leaking on every path. (``with`` forms are safe by construction and
   skipped.)

3. **Prepare without abort.** In functions whose name contains
   ``prepare`` or starts with ``reserve``, a loop performing per-member
   ``.reserve(...)`` calls must sit in a ``try`` whose handler calls a
   compensating ``unreserve``/``rollback``/``release``/``abort`` — a
   partial reserve abandoned by an exception is a permanent capacity
   leak (the ledger holds what no pod uses).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, Module, iter_classes, iter_methods, unparse

_EXEMPT_FNS = {
    "acquire", "release", "__enter__", "__exit__",
    "_acquire_restore", "_release_save", "try_acquire",
}
_COMPENSATORS = ("unreserve", "rollback", "release", "abort", "_gang_release")


def _resource_desc(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        if f.id == "open":
            return "open()"
        return None
    if not isinstance(f, ast.Attribute):
        return None
    base = unparse(f.value)
    if base == "os" and f.attr == "open":
        return "os.open()"
    if base == "socket" and f.attr in ("socket", "socketpair", "create_connection"):
        return f"socket.{f.attr}()"
    if base == "tempfile" and f.attr in ("mkstemp", "NamedTemporaryFile", "TemporaryFile"):
        return f"tempfile.{f.attr}()"
    if f.attr == "makefile":
        return ".makefile()"
    return None


def _call_closes(node: ast.AST, names: Set[str]) -> bool:
    """Does this subtree close any of ``names``? (``n.close()``,
    ``os.close(n)``, ``os.unlink`` is NOT a close — fds survive unlink.)"""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        f = sub.func
        if isinstance(f, ast.Attribute):
            if f.attr == "close" and isinstance(f.value, ast.Name) and f.value.id in names:
                return True
            if (
                f.attr == "close"
                and unparse(f.value) == "os"
                and sub.args
                and isinstance(sub.args[0], ast.Name)
                and sub.args[0].id in names
            ):
                return True
    return False


def _check_acquire(module: Module, owner: str, fn: ast.AST, findings: List[Finding]) -> None:
    name = getattr(fn, "name", "")
    if name in _EXEMPT_FNS:
        return
    acquires = [
        node
        for node in ast.walk(fn)
        if isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "acquire"
        # lock-shaped receivers only: lease electors and other acquire()
        # protocols have their own lifecycles (released on shutdown, not
        # per-call) and are not this rule's business
        and ("lock" in unparse(node.func.value).lower()
             or "cond" in unparse(node.func.value).lower()
             or "mutex" in unparse(node.func.value).lower())
    ]
    if not acquires:
        return
    has_finally_release = any(
        isinstance(node, ast.Try)
        and node.finalbody
        and any(
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr == "release"
            for stmt in node.finalbody
            for sub in ast.walk(stmt)
        )
        for node in ast.walk(fn)
    )
    if has_finally_release:
        return
    for node in acquires:
        findings.append(
            Finding(
                checker="excsafety",
                path=module.relpath,
                relpath=module.relpath,
                line=node.lineno,
                message=(
                    f"{unparse(node.func.value)}.acquire() in {owner} with no "
                    "finally-release — the lock is kept on every exception "
                    "path; use `with` or try/finally"
                ),
            )
        )


_SAFE_CALLS = {
    "str", "int", "float", "len", "repr", "print", "list", "dict", "set",
    "tuple", "sorted", "min", "max", "bool", "format",
}
_SAFE_CALL_PREFIXES = ("hashlib.", "logging.", "logger.", "time.", "os.path.")


class _ResourceState:
    __slots__ = ("names", "desc", "line", "secured", "closed_protected",
                 "leak_reported", "suspended")

    def __init__(self, names: Set[str], desc: str, line: int):
        self.names = names
        self.desc = desc
        self.line = line
        self.secured = False
        self.closed_protected = False
        self.leak_reported = False
        # True while walking except-handlers of the try the resource was
        # created in: on those paths the creation itself failed, so the
        # "leaks before secured" rule does not apply
        self.suspended = False


def _check_resources(module: Module, owner: str, fn: ast.AST, findings: List[Finding]) -> None:
    states: List[_ResourceState] = []

    def secure_targets(value: ast.AST, names: Set[str]) -> bool:
        """Does this expression consume/secure one of ``names``?
        Securing = stored to self/attribute/subscript, returned, yielded,
        or handed to os.fdopen (fd ownership transfer)."""
        for sub in ast.walk(value):
            if isinstance(sub, ast.Call):
                f = sub.func
                callee = unparse(f)
                if callee in ("os.fdopen", "fdopen"):
                    if any(isinstance(a, ast.Name) and a.id in names for a in sub.args):
                        return True
        return False

    def _executed_nodes(stmt: ast.AST):
        """ast.walk minus nested function/lambda bodies — a ``def`` is
        not executed at its definition point."""
        stack = [stmt]
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    continue
                stack.append(child)

    def has_risky_call(stmt: ast.AST, state: _ResourceState) -> Optional[str]:
        """First fallible call in ``stmt`` that is neither a close of the
        resource nor its own creation, else None."""
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return None  # a def/class statement executes no body code
        for sub in _executed_nodes(stmt):
            if isinstance(sub, ast.Call):
                f = sub.func
                text = unparse(f)
                if isinstance(f, ast.Attribute) and f.attr == "close":
                    continue
                if _resource_desc(sub) is not None:
                    continue
                # constructors/formatters that cannot meaningfully raise
                if text in _SAFE_CALLS or text.startswith(_SAFE_CALL_PREFIXES):
                    continue
                return text
        return None

    def protected_by(try_node: ast.Try, state: _ResourceState) -> bool:
        """The try's handlers or finally close the resource."""
        for h in try_node.handlers:
            if any(_call_closes(s, state.names) for s in h.body):
                return True
        if try_node.finalbody and any(
            _call_closes(s, state.names) for s in try_node.finalbody
        ):
            return True
        return False

    def walk_block(body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            # new resource assignments start tracking
            if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
                desc = _resource_desc(stmt.value)
                if desc is not None:
                    names: Set[str] = set()
                    attr_target = False
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            names.add(t.id)
                        elif isinstance(t, ast.Tuple):
                            elts = t.elts
                            if desc == "tempfile.mkstemp()":
                                # (fd, path): the fd is the resource, the
                                # path is a string
                                elts = elts[:1]
                            for e in elts:
                                if isinstance(e, ast.Name):
                                    names.add(e.id)
                        else:
                            attr_target = True  # self.x = open() — owned
                    if names and not attr_target:
                        states.append(_ResourceState(names, desc, stmt.lineno))
                    process_stmt(stmt, creating=True)
                    continue
            process_stmt(stmt, creating=False)
            # recurse into compound statements
            if isinstance(stmt, ast.Try):
                for st in states:
                    if not st.secured and protected_by(stmt, st):
                        st.closed_protected = True
                n_before = len(states)
                walk_block(stmt.body)
                born = states[n_before:]
                # on a handler path, the creation inside this try FAILED —
                # suspend its states so `raise WrappedError(...)` in the
                # handler is not misread as a leak-before-secure
                for st in born:
                    st.suspended = True
                for h in stmt.handlers:
                    walk_block(h.body)
                for st in born:
                    st.suspended = False
                walk_block(stmt.orelse)
                walk_block(stmt.finalbody)
            elif not isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                # nested defs/classes are not executed here — their bodies
                # are separate control flow (checked as their own functions)
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, attr, None)
                    if sub:
                        walk_block(sub)

    def process_stmt(stmt: ast.stmt, creating: bool) -> None:
        for st in states:
            if st.secured or st.suspended:
                continue
            # securing forms
            if isinstance(stmt, (ast.Return, ast.Expr)) and isinstance(
                getattr(stmt, "value", None), (ast.Name, ast.Tuple)
            ):
                v = stmt.value
                elts = v.elts if isinstance(v, ast.Tuple) else [v]
                if isinstance(stmt, ast.Return) and any(
                    isinstance(e, ast.Name) and e.id in st.names for e in elts
                ):
                    st.secured = True
                    continue
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)) and isinstance(
                        stmt.value, ast.Name
                    ) and stmt.value.id in st.names:
                        st.secured = True
                if secure_targets(stmt.value, st.names):
                    st.secured = True
            if isinstance(stmt, ast.Expr) and secure_targets(stmt.value, st.names):
                st.secured = True
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    if secure_targets(item.context_expr, st.names):
                        st.secured = True
            if st.secured:
                continue
            if _call_closes(stmt, st.names):
                # an unconditional close before any risky call: the
                # resource's lifetime ended cleanly (risky-before-close is
                # caught below, at the risky call, not here)
                st.secured = True
                continue
            if creating:
                continue
            if st.closed_protected or st.leak_reported:
                continue
            if isinstance(stmt, ast.Try):
                if protected_by(stmt, st):
                    st.closed_protected = True
                continue
            risky = has_risky_call(stmt, st)
            if risky is not None:
                st.leak_reported = True
                findings.append(
                    Finding(
                        checker="excsafety",
                        path=module.relpath,
                        relpath=module.relpath,
                        line=st.line,
                        message=(
                            f"{st.desc} in {owner} may leak: '{risky}' can "
                            "raise before the resource is stored or closed — "
                            "use with/try-finally or close in the except path"
                        ),
                    )
                )

    body = fn.body if isinstance(fn.body, list) else [fn.body]
    walk_block(body)
    for st in states:
        if st.secured or st.closed_protected or st.leak_reported:
            continue
        findings.append(
            Finding(
                checker="excsafety",
                path=module.relpath,
                relpath=module.relpath,
                line=st.line,
                message=f"{st.desc} in {owner} is never closed on any path",
            )
        )


def _check_prepare_abort(
    module: Module, owner: str, fn: ast.FunctionDef, findings: List[Finding]
) -> None:
    name = fn.name.lower()
    if "prepare" not in name and not name.startswith("reserve"):
        return

    def loop_reserves(loop: ast.AST) -> Optional[int]:
        for sub in ast.walk(loop):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "reserve"
            ):
                return sub.lineno
        return None

    def handler_compensates(try_node: ast.Try) -> bool:
        for h in try_node.handlers:
            for s in h.body:
                for sub in ast.walk(s):
                    if isinstance(sub, ast.Call):
                        callee = (
                            sub.func.attr
                            if isinstance(sub.func, ast.Attribute)
                            else getattr(sub.func, "id", "")
                        )
                        if any(c in callee for c in _COMPENSATORS):
                            return True
        return False

    protected_loops: Set[int] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Try) and handler_compensates(node):
            for sub in ast.walk(node):
                if isinstance(sub, (ast.For, ast.While)):
                    protected_loops.add(id(sub))
    for node in ast.walk(fn):
        if isinstance(node, (ast.For, ast.While)) and id(node) not in protected_loops:
            line = loop_reserves(node)
            if line is not None:
                findings.append(
                    Finding(
                        checker="excsafety",
                        path=module.relpath,
                        relpath=module.relpath,
                        line=line,
                        message=(
                            f"per-member reserve loop in {owner} has no "
                            "compensating unreserve/rollback handler — a "
                            "partial reserve abandoned mid-loop leaks capacity"
                        ),
                    )
                )


def check(modules: Sequence[Module]) -> List[Finding]:
    findings: List[Finding] = []
    for m in modules:
        if m.relpath.replace("\\", "/").endswith("utils/lockorder.py"):
            continue  # the lock instrumentation itself
        claimed = set()
        for cls in iter_classes(m):
            for method in iter_methods(cls):
                claimed.add(id(method))
                owner = f"{cls.name}.{method.name}"
                _check_acquire(m, owner, method, findings)
                _check_resources(m, owner, method, findings)
                _check_prepare_abort(m, owner, method, findings)
        for node in m.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if id(node) in claimed:
                    continue
                _check_acquire(m, node.name, node, findings)
                _check_resources(m, node.name, node, findings)
                _check_prepare_abort(m, node.name, node, findings)
    findings.sort(key=lambda f: (f.relpath, f.line, f.message))
    return findings
