"""Checker 6: thread lifecycle — silent death, daemon-under-lock, and
unbounded shutdown joins.

The PR 6 review found a standby replicator thread dead of an uncaught
exception while ``/readyz`` reported ok — the bug class this checker
makes structural. Three rules:

1. **Exception routing.** Every ``threading.Thread(target=...)`` whose
   target resolves statically (a ``self._method``, a local/module
   ``def``, or a ``threading.Thread`` subclass's ``run``) must have
   *top-level exception routing*: a ``try`` that is a direct child of
   the target's body (or of a top-level loop's body) carrying a broad
   handler (``except Exception``/``BaseException``/bare, body not just
   ``pass``) or a ``finally`` (teardown-as-routing: the ``finally`` can
   flip a health flag on the way out). Anything narrower means an
   unexpected exception kills the thread while every probe stays green.
   Targets that are deliberate fire-and-forget carry a waiver comment —
   ``#: thread: fire-and-forget`` — on the ``Thread(...)`` line, the
   line above it, or the target's ``def`` line. Foreign targets
   (``self._httpd.serve_forever``) are skipped: not ours to instrument.

2. **Daemon spawn under a lock.** Constructing a ``Thread`` while
   lexically holding a named lock is flagged: the child can start and
   immediately contend (or deadlock) on the very lock its parent still
   holds, and the spawn itself (interpreter bookkeeping) is slow work
   under a lock either way.

3. **Unbounded shutdown joins.** ``.join()`` with neither a positional
   timeout nor a ``timeout=`` keyword inside a method named ``stop`` /
   ``close`` / ``shutdown`` / ``teardown`` / ``__exit__`` wedges
   shutdown forever if the thread is stuck — exactly when it is most
   likely to be stuck.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .core import Finding, Module, iter_classes, iter_methods, unparse
from .lockgraph import _ModuleLocks, _collect_class_info, resolve_lock_node

_WAIVER_RE = re.compile(r"#:\s*thread:\s*fire-and-forget")
_SHUTDOWN_METHODS = {"stop", "close", "shutdown", "teardown", "__exit__"}
_BROAD = {"Exception", "BaseException"}


def _is_thread_call(call: ast.Call) -> bool:
    f = call.func
    name = f.id if isinstance(f, ast.Name) else getattr(f, "attr", None)
    return name == "Thread"


def _target_expr(call: ast.Call) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == "target":
            return kw.value
    return None


def _line_waived(module: Module, *linenos: int) -> bool:
    for ln in linenos:
        for cand in (ln, ln - 1):
            if 1 <= cand <= len(module.lines) and _WAIVER_RE.search(
                module.lines[cand - 1]
            ):
                return True
    return False


def _handler_is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        broad = True
    else:
        names = []
        t = handler.type
        elts = t.elts if isinstance(t, ast.Tuple) else [t]
        for e in elts:
            names.append(e.id if isinstance(e, ast.Name) else getattr(e, "attr", ""))
        broad = any(n in _BROAD for n in names)
    if not broad:
        return False
    # a handler that only ``pass``es swallows the death without routing
    # it anywhere — that is silent death with extra steps
    return not all(isinstance(s, ast.Pass) for s in handler.body)


def _has_toplevel_routing(fn: ast.AST) -> bool:
    """A Try with a broad handler or a finally, sitting either directly
    in the function body or directly in the body of a top-level loop."""

    def try_ok(node: ast.stmt) -> bool:
        return isinstance(node, ast.Try) and (
            bool(node.finalbody) or any(_handler_is_broad(h) for h in node.handlers)
        )

    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        if try_ok(stmt):
            return True
        if isinstance(stmt, (ast.While, ast.For)):
            if any(try_ok(s) for s in stmt.body):
                return True
        if isinstance(stmt, ast.With):
            # `with ...:` wrapping the whole loop/try is common shape
            if any(
                try_ok(s)
                or (isinstance(s, (ast.While, ast.For)) and any(try_ok(x) for x in s.body))
                for s in stmt.body
            ):
                return True
    return False


class _FnIndex:
    """Resolution of thread targets: methods by (class, name), local defs
    by enclosing function, module defs by name."""

    def __init__(self, module: Module):
        self.module = module
        self.module_defs: Dict[str, ast.AST] = {}
        for node in module.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module_defs[node.name] = node


def _local_defs(fn: ast.AST) -> Dict[str, ast.AST]:
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
            out.setdefault(node.name, node)
    return out


def _check_spawns(
    module: Module,
    owner: str,
    fn: ast.AST,
    methods: Dict[str, ast.AST],
    idx: _FnIndex,
    info,
    mod_locks: _ModuleLocks,
    by_bare_name,
    findings: List[Finding],
) -> None:
    locals_ = _local_defs(fn)

    def visit(node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in node.items:
                n = resolve_lock_node(item.context_expr, info, mod_locks, by_bare_name)
                if n is not None:
                    inner.add(n)
            for stmt in node.body:
                visit(stmt, frozenset(inner))
            return
        if isinstance(node, ast.Call) and _is_thread_call(node):
            if held:
                findings.append(
                    Finding(
                        checker="threads",
                        path=module.relpath,
                        relpath=module.relpath,
                        line=node.lineno,
                        message=(
                            f"thread spawned while holding {', '.join(sorted(held))} "
                            f"(in {owner}) — spawn outside the lock"
                        ),
                    )
                )
            _check_target(node)
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    def _check_target(call: ast.Call) -> None:
        target = _target_expr(call)
        if target is None:
            return
        resolved: Optional[ast.AST] = None
        tname = ""
        if isinstance(target, ast.Attribute):
            base = target.value
            if isinstance(base, ast.Name) and base.id == "self":
                resolved = methods.get(target.attr)
                tname = target.attr
            else:
                return  # foreign target (library object) — not ours
        elif isinstance(target, ast.Name):
            resolved = locals_.get(target.id) or idx.module_defs.get(target.id)
            tname = target.id
        elif isinstance(target, ast.Lambda):
            findings.append(
                Finding(
                    checker="threads",
                    path=module.relpath,
                    relpath=module.relpath,
                    line=call.lineno,
                    message=(
                        f"lambda thread target in {owner}: exceptions are "
                        "unroutable — use a def with try/except or waive"
                    ),
                )
            )
            return
        if resolved is None:
            return
        if _line_waived(module, call.lineno, resolved.lineno):
            return
        if not _has_toplevel_routing(resolved):
            findings.append(
                Finding(
                    checker="threads",
                    path=module.relpath,
                    relpath=module.relpath,
                    line=resolved.lineno,
                    message=(
                        f"thread target '{tname}' (spawned in {owner}) has no "
                        "top-level exception routing — an uncaught exception "
                        "kills it silently; route to health/restart or waive "
                        "with '#: thread: fire-and-forget'"
                    ),
                )
            )

    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        visit(stmt, frozenset())


def _check_shutdown_joins(
    module: Module, owner: str, fn: ast.FunctionDef, findings: List[Finding]
) -> None:
    if fn.name not in _SHUTDOWN_METHODS:
        return
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr == "join"):
            continue
        if node.args or any(kw.arg == "timeout" for kw in node.keywords):
            continue
        base = unparse(f.value)
        if "." in base and not base.startswith("self"):
            continue  # os.path.join etc.
        findings.append(
            Finding(
                checker="threads",
                path=module.relpath,
                relpath=module.relpath,
                line=node.lineno,
                message=(
                    f"{base}.join() without timeout in shutdown path "
                    f"{owner} — a stuck thread wedges shutdown forever"
                ),
            )
        )


def check(modules: Sequence[Module]) -> List[Finding]:
    findings: List[Finding] = []
    # class infos for lock resolution (daemon-under-lock rule)
    by_bare_name: Dict[str, List] = {}
    infos: Dict[Tuple[str, str], object] = {}
    for m in modules:
        for cls in iter_classes(m):
            info = _collect_class_info(m, cls)
            infos[(m.modname, cls.name)] = info
            by_bare_name.setdefault(cls.name, []).append(info)
    for m in modules:
        idx = _FnIndex(m)
        ml = _ModuleLocks(m)
        for cls in iter_classes(m):
            info = infos[(m.modname, cls.name)]
            methods = {meth.name: meth for meth in iter_methods(cls)}
            # Thread subclasses: run() is an implicit target of start()
            bases = {unparse(b).rsplit(".", 1)[-1] for b in cls.bases}
            if "Thread" in bases and "run" in methods:
                run = methods["run"]
                if not _line_waived(m, cls.lineno, run.lineno) and not _has_toplevel_routing(run):
                    findings.append(
                        Finding(
                            checker="threads",
                            path=m.relpath,
                            relpath=m.relpath,
                            line=run.lineno,
                            message=(
                                f"Thread subclass {cls.name}.run has no "
                                "top-level exception routing — an uncaught "
                                "exception kills it silently; route to "
                                "health/restart or waive with "
                                "'#: thread: fire-and-forget'"
                            ),
                        )
                    )
            for method in iter_methods(cls):
                owner = f"{cls.name}.{method.name}"
                _check_spawns(m, owner, method, methods, idx, info, ml,
                              by_bare_name, findings)
                _check_shutdown_joins(m, owner, method, findings)
        claimed = set()
        for cls in iter_classes(m):
            for method in iter_methods(cls):
                claimed.add(id(method))
        for node in m.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if id(node) in claimed:
                    continue
                _check_spawns(m, node.name, node, {}, idx, None, ml, by_bare_name,
                              findings)
    findings.sort(key=lambda f: (f.relpath, f.line, f.message))
    return findings
