"""Checker 4: string-keyed registry consistency.

Two registries in this codebase are keyed by inline strings, and both
have drifted in past PRs (a fault site that no rule ever matches fires
nothing silently; a metric family created under an undeclared name never
shows up where dashboards look):

- **fault sites**: every literal site passed to ``*.check(site)`` /
  ``*.maybe_raise(site)`` on a ``faults`` object must be a member of
  ``faults.plan.KNOWN_SITES``; every literal ``FaultRule(site=...)``
  pattern must ``fnmatch`` at least one known site;
- **metric names**: every literal name passed to ``gauge_vec`` /
  ``counter_vec`` / ``histogram_vec`` must be a member of
  ``metrics.METRIC_NAMES`` (the single declaration point — families
  built from f-strings in ``metrics.py`` are enumerated there
  explicitly).

Both registries are read straight from the AST of their defining module
(a ``frozenset({...})`` / set/tuple literal assignment), so the checker
needs no imports of the package under analysis.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import List, Optional, Sequence, Set

from .core import Finding, Module, literal_str, unparse

_VEC_FACTORIES = {"gauge_vec", "counter_vec", "histogram_vec"}
_FAULT_METHODS = {"check", "maybe_raise"}


def _literal_str_set(module: Module, varname: str) -> Optional[Set[str]]:
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == varname:
                    try:
                        val = ast.literal_eval(node.value)
                    except ValueError:
                        # frozenset({...}) is a Call — eval its single arg
                        if (
                            isinstance(node.value, ast.Call)
                            and unparse(node.value.func).endswith("frozenset")
                            and node.value.args
                        ):
                            try:
                                val = ast.literal_eval(node.value.args[0])
                            except ValueError:
                                return None
                        else:
                            return None
                    if isinstance(val, (set, frozenset, tuple, list)):
                        return {str(v) for v in val}
    return None


def _find_module(modules: Sequence[Module], suffix: str) -> Optional[Module]:
    for m in modules:
        if m.relpath.replace("\\", "/").endswith(suffix):
            return m
    return None


def check(modules: Sequence[Module]) -> List[Finding]:
    findings: List[Finding] = []

    plan_mod = _find_module(modules, "faults/plan.py")
    known_sites = (
        _literal_str_set(plan_mod, "KNOWN_SITES") if plan_mod is not None else None
    )
    metrics_mod = _find_module(modules, "metrics.py")
    metric_names = (
        _literal_str_set(metrics_mod, "METRIC_NAMES")
        if metrics_mod is not None
        else None
    )

    if plan_mod is not None and known_sites is None:
        findings.append(
            Finding(
                checker="registry",
                path=plan_mod.path,
                relpath=plan_mod.relpath,
                line=1,
                message="faults/plan.py must declare KNOWN_SITES as a literal set of site names",
            )
        )
    if metrics_mod is not None and metric_names is None:
        findings.append(
            Finding(
                checker="registry",
                path=metrics_mod.path,
                relpath=metrics_mod.relpath,
                line=1,
                message="metrics.py must declare METRIC_NAMES as a literal set of family names",
            )
        )

    for m in modules:
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not isinstance(f, ast.Attribute):
                continue
            # fault sites
            if (
                known_sites is not None
                and f.attr in _FAULT_METHODS
                and "faults" in unparse(f.value)
                and node.args
            ):
                site = literal_str(node.args[0])
                if site is not None and site not in known_sites:
                    findings.append(
                        Finding(
                            checker="registry",
                            path=m.path,
                            relpath=m.relpath,
                            line=node.lineno,
                            message=(
                                f"fault site '{site}' is not registered in "
                                "faults.plan.KNOWN_SITES"
                            ),
                        )
                    )
            # FaultRule site patterns
            if (
                known_sites is not None
                and (
                    (isinstance(f.value, ast.Name) and f.attr == "FaultRule")
                    or unparse(f).endswith("FaultRule")
                )
            ):
                pattern = None
                if node.args:
                    pattern = literal_str(node.args[0])
                for kw in node.keywords:
                    if kw.arg == "site":
                        pattern = literal_str(kw.value)
                if pattern is not None and not any(
                    fnmatch.fnmatch(s, pattern) for s in known_sites
                ):
                    findings.append(
                        Finding(
                            checker="registry",
                            path=m.path,
                            relpath=m.relpath,
                            line=node.lineno,
                            message=(
                                f"FaultRule pattern '{pattern}' matches no "
                                "site in faults.plan.KNOWN_SITES"
                            ),
                        )
                    )
            # metric family names
            if metric_names is not None and f.attr in _VEC_FACTORIES and node.args:
                name = literal_str(node.args[0])
                if name is not None and name not in metric_names:
                    findings.append(
                        Finding(
                            checker="registry",
                            path=m.path,
                            relpath=m.relpath,
                            line=node.lineno,
                            message=(
                                f"metric family '{name}' is not declared in "
                                "metrics.METRIC_NAMES"
                            ),
                        )
                    )
    # plain FaultRule(...) constructor calls by bare name
    if known_sites is not None:
        for m in modules:
            for node in ast.walk(m.tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "FaultRule"
                ):
                    pattern = None
                    if node.args:
                        pattern = literal_str(node.args[0])
                    for kw in node.keywords:
                        if kw.arg == "site":
                            pattern = literal_str(kw.value)
                    if pattern is not None and not any(
                        fnmatch.fnmatch(s, pattern) for s in known_sites
                    ):
                        findings.append(
                            Finding(
                                checker="registry",
                                path=m.path,
                                relpath=m.relpath,
                                line=node.lineno,
                                message=(
                                    f"FaultRule pattern '{pattern}' matches no "
                                    "site in faults.plan.KNOWN_SITES"
                                ),
                            )
                        )
    return findings
