"""Checker 1: guarded-by discipline.

Shared mutable attributes are declared guarded either way:

- a class-level table for existing code::

      GUARDED_BY = {"_values": "self._lock", "_queue": ("self._lock",)}

  (values are the lock expression as written at the ``with`` site; a
  tuple means holding ANY of the listed locks satisfies the guard);

- or an inline annotation on the attribute declaration::

      self._values = {}  #: guarded-by: self._lock

  (also recognized on the line directly above the assignment).

Every ``self.<attr>`` read/write of a guarded attribute must then sit
lexically inside a ``with <lock>:`` block for one of its guards.
Method-boundary rules:

- ``__init__``/``__new__``/``__del__`` are exempt (construction and
  teardown happen-before/after sharing);
- methods whose name ends in ``_locked`` are callee-side helpers whose
  contract is "caller holds the lock" — they are treated as holding every
  guard of their class (and should call ``lockorder.assert_held`` when
  the runtime assassin is on);
- nested functions inherit the locks held at their definition site (the
  dominant in-tree shape is a closure invoked synchronously under the
  lock; a closure stashed and called later must be waived explicitly).

``threading.Condition(self._lock)`` aliases: holding the condition IS
holding the lock, so either expression satisfies a guard naming the
other.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .core import Finding, Module, iter_classes, iter_methods, unparse

_INLINE_RE = re.compile(r"#:\s*guarded-by:\s*(?P<expr>[^#]+?)\s*$")
_ATTR_ASSIGN_RE = re.compile(r"^\s*self\.(?P<attr>\w+)\s*(?::[^=]+)?=[^=]")

EXEMPT_METHODS = {"__init__", "__new__", "__del__", "__post_init__"}


def _norm_expr(node: ast.AST) -> str:
    """Canonical text of a lock expression: calls lose their arguments
    (``self._key_lock(key)`` -> ``self._key_lock()``) and subscripts lose
    their index (``self._conds[i]`` -> ``self._conds``), so guards over
    accessor methods and lock collections can be written generically."""
    if isinstance(node, ast.Call):
        return unparse(node.func) + "()"
    while isinstance(node, ast.Subscript):
        node = node.value
    return unparse(node)


def _norm_str(s: str) -> str:
    s = s.strip()
    m = re.match(r"^(?P<base>[\w\.\[\]'\"]+)\(.*\)$", s)
    if m and "(" in s:
        return m.group("base") + "()"
    return re.sub(r"(\[[^\]]*\])+$", "", s)


class _GuardSpec:
    """Per-class guard table + condition/lock aliases."""

    def __init__(self) -> None:
        self.attrs: Dict[str, Tuple[str, ...]] = {}
        self.aliases: Dict[str, Set[str]] = {}

    def add(self, attr: str, guards) -> None:
        if isinstance(guards, str):
            guards = (guards,)
        self.attrs[attr] = tuple(_norm_str(g) for g in guards)

    def add_alias(self, a: str, b: str) -> None:
        self.aliases.setdefault(a, set()).add(b)
        self.aliases.setdefault(b, set()).add(a)

    def satisfied(self, attr: str, held: FrozenSet[str]) -> bool:
        for g in self.attrs[attr]:
            if g in held:
                return True
            if any(alias in held for alias in self.aliases.get(g, ())):
                return True
        return False


def _collect_table(cls: ast.ClassDef) -> Optional[Dict[str, object]]:
    for node in cls.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "GUARDED_BY":
                try:
                    table = ast.literal_eval(value)
                except ValueError:
                    return None
                return table if isinstance(table, dict) else None
    return None


def _collect_inline(module: Module, cls: ast.ClassDef, spec: _GuardSpec) -> None:
    start = cls.lineno - 1
    end = max(
        (getattr(n, "end_lineno", n.lineno) for n in ast.walk(cls) if hasattr(n, "lineno")),
        default=cls.lineno,
    )
    lines = module.lines
    for i in range(start, min(end, len(lines))):
        m = _INLINE_RE.search(lines[i])
        if not m:
            continue
        guard = _norm_str(m.group("expr"))
        am = _ATTR_ASSIGN_RE.match(lines[i])
        if am is None and i + 1 < len(lines) and lines[i].strip().startswith("#:"):
            # standalone comment line: annotates the assignment below
            am = _ATTR_ASSIGN_RE.match(lines[i + 1])
        if am is not None:
            spec.add(am.group("attr"), guard)


def _collect_aliases(cls: ast.ClassDef, spec: _GuardSpec) -> None:
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        fn = node.value
        callee = unparse(fn.func)
        if not callee.endswith("Condition"):
            continue
        if not fn.args:
            continue
        lock_expr = _norm_expr(fn.args[0])
        for t in node.targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                spec.add_alias(f"self.{t.attr}", lock_expr)


class _MethodVisitor:
    """Walks one method body tracking lexically-held locks."""

    def __init__(
        self,
        module: Module,
        cls_name: str,
        method: str,
        spec: _GuardSpec,
        findings: List[Finding],
        aliases: Optional[Dict[str, str]] = None,
    ):
        self.module = module
        self.cls_name = cls_name
        self.method = method
        self.spec = spec
        self.findings = findings
        # local-name -> normalized self-expr (``cond = self._conds[i]``
        # makes ``with cond:`` count as holding self._conds); collected
        # flow-insensitively over the whole method
        self.aliases = aliases or {}

    def run(self, body: Sequence[ast.stmt], held: FrozenSet[str]) -> None:
        for stmt in body:
            self._visit(stmt, held)

    def _visit(self, node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in node.items:
                self._scan_expr(item.context_expr, held)
                norm = _norm_expr(item.context_expr)
                inner.add(self.aliases.get(norm, norm))
            self.run(node.body, frozenset(inner))
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: inherits the definition site's held set (see
            # module docstring); decorators/defaults evaluate here
            for dec in node.decorator_list:
                self._scan_expr(dec, held)
            for d in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None
            ]:
                self._scan_expr(d, held)
            self.run(node.body, held)
            return
        if isinstance(node, ast.Lambda):
            self._visit(node.body, held)
            return
        if isinstance(node, ast.expr):
            self._scan_expr(node, held)
            return
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)

    def _scan_expr(self, node: ast.AST, held: FrozenSet[str]) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Lambda,)):
                continue  # walked anyway; held set identical
            if (
                isinstance(sub, ast.Attribute)
                and isinstance(sub.value, ast.Name)
                and sub.value.id == "self"
                and sub.attr in self.spec.attrs
            ):
                if not self.spec.satisfied(sub.attr, held):
                    kind = "write" if isinstance(sub.ctx, (ast.Store, ast.Del)) else "read"
                    guards = " | ".join(self.spec.attrs[sub.attr])
                    self.findings.append(
                        Finding(
                            checker="guarded",
                            path=self.module.path,
                            relpath=self.module.relpath,
                            line=sub.lineno,
                            message=(
                                f"{kind} of '{sub.attr}' (guarded by {guards}) "
                                f"outside its lock in {self.cls_name}.{self.method}"
                            ),
                        )
                    )


def _local_lock_aliases(method: ast.AST) -> Dict[str, str]:
    """``name = self.<something>`` assignments anywhere in the method:
    name -> normalized self-expression. Flow-insensitive (good enough for
    the in-tree ``cond = self._conds[i]`` shape; a name rebound to two
    different locks would resolve to the last one seen)."""
    out: Dict[str, str] = {}
    for node in ast.walk(method):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                norm = _norm_expr(node.value)
                if norm.startswith("self."):
                    out[t.id] = norm
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            # `for cond in self._conds:` / `for i, cond in
            # enumerate(self._conds):` — the loop variable iterates the
            # lock collection
            it = node.iter
            target = node.target
            if (
                isinstance(it, ast.Call)
                and isinstance(it.func, ast.Name)
                and it.func.id == "enumerate"
                and it.args
            ):
                it = it.args[0]
                if isinstance(target, ast.Tuple) and len(target.elts) == 2:
                    target = target.elts[1]
            norm = _norm_expr(it)
            if norm.startswith("self.") and isinstance(target, ast.Name):
                out[target.id] = norm
    return out


def check_module(module: Module) -> List[Finding]:
    findings: List[Finding] = []
    for cls in iter_classes(module):
        spec = _GuardSpec()
        table = _collect_table(cls)
        if table:
            for attr, guards in table.items():
                spec.add(str(attr), guards)
        _collect_inline(module, cls, spec)
        if not spec.attrs:
            continue
        _collect_aliases(cls, spec)
        for method in iter_methods(cls):
            if method.name in EXEMPT_METHODS:
                continue
            if method.name.endswith("_locked"):
                # contract: caller holds the lock — treat as holding all
                held = frozenset(
                    g for guards in spec.attrs.values() for g in guards
                )
            else:
                held = frozenset()
            aliases = _local_lock_aliases(method)
            _MethodVisitor(
                module, cls.name, method.name, spec, findings, aliases
            ).run(method.body, held)
    return findings


def check(modules: Sequence[Module]) -> List[Finding]:
    out: List[Finding] = []
    for m in modules:
        out.extend(check_module(m))
    return out
