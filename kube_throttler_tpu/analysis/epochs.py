"""Checker 13 (gen-4): epoch-coherence domination over the verdict planes.

PR 17's interned-verdict cache proves freshness by epoch sums: a
PreFilter verdict is a pure function of (request-shape id, accel class,
matched cols, per-col state), and every mutation that can change a
verdict must bump ``col_epoch[col]`` / ``global_epoch`` under the
owner's main lock. A write that skips the bump does not crash anything
— it silently serves a stale admission verdict at cache-hit speed
(134k decisions/s of quiet wrongness), which is exactly the bug shape
static analysis exists for.

``ops/schema.py`` declares the covered state as a literal set
(``VERDICT_EPOCH_PLANES`` — read from the AST here, the same registry
idiom as ``INT64_MILLI_PLANES``): the st_* flip planes, the
threshold/spec columns, the usage and reservation ledgers, and the
per-accel-class override table. The checker scans ``engine/``,
``sharding/``, and ``plugin/`` for **covered writes**:

- direct stores — ``X.<plane>[...] = ``, ``X.<plane> = ``, augmented
  assigns, and mutating container calls (``.pop``/``.clear``/
  ``.update``/``.fill``) on a covered attribute;
- indirect stores — a call passing a covered plane name as a string
  literal (the ``_amount_into_row(amount, "res_cnt", ...)`` shape:
  devicestate routes row encodes through ``getattr``-named planes, so
  the plane name at the call site IS the write).

Every covered write must be **dominated by an epoch bump**: the writing
function itself bumps (writes ``col_epoch``/``global_epoch``/
``_epochs``/``_global_epoch``, calls a ``bump_epoch*`` /
``_bump_pod_epochs`` / ``_bump_global_epoch`` / ``invalidate_all``
provider, or carries an inline ``#: epoch-bumps:`` annotation at its
``def``), or EVERY caller — resolved interprocedurally to fixpoint over
the same call shapes the lockorder/blocking checkers resolve
(``self.m()``, ``self.attr.m()`` with one level of attribute-type
inference, unique bare-name module functions) — is recursively
dominated. ``__init__`` is exempt (construction precedes sharing; the
epoch plane itself is allocated there).

Vetted exceptions go in ``epoch_allow.txt``, one per line::

    engine.devicestate.KindState.ensure_capacity -> thr_cnt  # growth zero-fills invalid cols only

keyed ``(context, plane)`` with a mandatory justification. Entries
matching no write site are stale and FAIL the run (``--prune-stale``
deletes them). The runtime companion (``utils/epochassert.py``,
``KT_EPOCH_ASSERT=1``) keeps the allow file honest: a waived-but-wrong
entry surfaces as a StaleVerdict report in the armed suite.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, Module, iter_classes, iter_methods, load_pair_allowlist

_SCOPE_PREFIXES = ("engine/", "sharding/", "plugin/")

# minimal fallback when the declaring schema module is outside the
# analyzed root (fixture trees declare their own registry)
_FALLBACK_PLANES = frozenset(
    {"thr_cnt", "used_cnt", "res_cnt", "st_cnt_throttled"}
)

# writes to these attributes ARE the bump
_EPOCH_ATTRS = {"col_epoch", "global_epoch", "_epochs", "_global_epoch"}
# calling one of these (or any bump_epoch*-named function) provides the bump
_BUMP_CALLS = {"bump_epochs_for", "_bump_pod_epochs", "_bump_global_epoch", "invalidate_all"}
_MUTATING_METHODS = {"pop", "clear", "update", "fill", "setdefault"}

_INLINE_RE = re.compile(r"#:\s*epoch-bumps:")

EXEMPT_METHODS = {"__init__"}


def in_scope(module: Module) -> bool:
    rel = module.relpath.replace("\\", "/")
    return rel.startswith(_SCOPE_PREFIXES)


def load_planes(modules: Sequence[Module]) -> Set[str]:
    """``VERDICT_EPOCH_PLANES`` literal from ops/schema.py's AST; the
    checked-in fallback only applies when the declaring module is outside
    the analyzed root."""
    for m in modules:
        if not m.relpath.replace("\\", "/").endswith("schema.py"):
            continue
        for node in m.tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
                value = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
                value = node.value
            else:
                continue
            for t in targets:
                if isinstance(t, ast.Name) and t.id == "VERDICT_EPOCH_PLANES":
                    # the registry idiom wraps the literal in frozenset(...)
                    # (or set(...)); literal_eval can't evaluate a Call, so
                    # unwrap to the underlying set/list/tuple display first
                    if (
                        isinstance(value, ast.Call)
                        and isinstance(value.func, ast.Name)
                        and value.func.id in ("frozenset", "set")
                        and len(value.args) == 1
                        and not value.keywords
                    ):
                        value = value.args[0]
                    try:
                        got = ast.literal_eval(value)
                    except ValueError:
                        continue
                    return {str(v) for v in got}
    return set(_FALLBACK_PLANES)


def _annotated_bump(m: Module, fn: ast.AST) -> bool:
    """True when the ``def`` line (or the line above it) carries an
    inline ``#: epoch-bumps:`` annotation."""
    for lineno in (fn.lineno, fn.lineno - 1):
        i = lineno - 1
        if 0 <= i < len(m.lines) and _INLINE_RE.search(m.lines[i]):
            return True
    return False


def _target_attr(node: ast.AST) -> Optional[str]:
    """The attribute name written by an assignment target: ``X.attr``,
    ``X.attr[...]``, or a plane-named bare name ONLY when subscripted
    (``plane[...] = `` may store through a local alias of the plane; a
    bare ``plane = ...`` rebinds a local and writes nothing shared)."""
    subscripted = isinstance(node, ast.Subscript)
    if subscripted:
        node = node.value
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name) and subscripted:
        return node.id
    return None


class _FnScan:
    """One function's covered writes, bump evidence, and call refs."""

    def __init__(self) -> None:
        self.writes: List[Tuple[str, int]] = []  # (plane, line)
        self.bumps = False
        # ref is ("self", m) | ("attr", a, m) | ("name", f)
        self.calls: List[Tuple[Tuple[str, ...], int]] = []


def _scan_function(m: Module, fn: ast.AST, planes: Set[str], out: _FnScan) -> None:
    if _annotated_bump(m, fn):
        out.bumps = True
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                attr = _target_attr(t)
                if attr in _EPOCH_ATTRS:
                    out.bumps = True
                elif attr in planes:
                    out.writes.append((attr, node.lineno))
        elif isinstance(node, ast.Call):
            f = node.func
            # mutating container calls on a covered plane / bump providers
            if isinstance(f, ast.Attribute):
                if isinstance(f.value, ast.Attribute):
                    owner = f.value.attr
                    if f.attr in _MUTATING_METHODS and owner in planes:
                        out.writes.append((owner, node.lineno))
                    if f.attr in _MUTATING_METHODS and owner in _EPOCH_ATTRS:
                        out.bumps = True
                name = f.attr
            elif isinstance(f, ast.Name):
                name = f.id
            else:
                name = ""
            if name.startswith("bump_epoch") or name in _BUMP_CALLS:
                out.bumps = True
                continue
            # indirect store: a covered plane name passed as a string
            # literal (the getattr-named row-encode shape)
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if (
                    isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                    and arg.value in planes
                ):
                    out.writes.append((arg.value, node.lineno))
            # call refs for the caller graph
            if isinstance(f, ast.Attribute):
                base = f.value
                if isinstance(base, ast.Name) and base.id == "self":
                    out.calls.append((("self", f.attr), node.lineno))
                elif (
                    isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"
                ):
                    out.calls.append((("attr", base.attr, f.attr), node.lineno))
            elif isinstance(f, ast.Name):
                out.calls.append((("name", f.id), node.lineno))


def check(
    modules: Sequence[Module],
    allowlist_path: Optional[str] = None,
    stale_out: Optional[List[Tuple[str, str]]] = None,
) -> List[Finding]:
    from .lockgraph import _ClassInfo, _collect_class_info

    planes = load_planes(modules)

    classes: Dict[str, _ClassInfo] = {}
    by_bare_name: Dict[str, List[_ClassInfo]] = {}
    for m in modules:
        for cls in iter_classes(m):
            info = _collect_class_info(m, cls)
            classes[info.qual] = info
            by_bare_name.setdefault(cls.name, []).append(info)

    scans: Dict[Tuple[str, str], _FnScan] = {}
    scan_meta: Dict[Tuple[str, str], str] = {}  # key -> relpath
    module_fns: Dict[str, List[Tuple[str, str]]] = {}
    for m in modules:
        if not in_scope(m):
            continue
        method_ids = set()
        for cls in iter_classes(m):
            qual = f"{m.modname}.{cls.name}"
            for method in iter_methods(cls):
                method_ids.add(id(method))
                s = _FnScan()
                _scan_function(m, method, planes, s)
                scans[(qual, method.name)] = s
                scan_meta[(qual, method.name)] = m.relpath
        for node in m.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if id(node) in method_ids:
                    continue
                s = _FnScan()
                _scan_function(m, node, planes, s)
                key = (m.modname, node.name)
                scans[key] = s
                scan_meta[key] = m.relpath
                module_fns.setdefault(node.name, []).append(key)

    def resolve(key: Tuple[str, str], ref: Tuple[str, ...]) -> Optional[Tuple[str, str]]:
        owner, _ = key
        if ref[0] == "self":
            callee = (owner, ref[1])
            return callee if callee in scans else None
        if ref[0] == "attr":
            info = classes.get(owner)
            if info is None:
                return None
            tname = info.attr_types.get(ref[1])
            if tname is None:
                return None
            cands = by_bare_name.get(tname, [])
            if len(cands) == 1:
                callee = (cands[0].qual, ref[2])
                return callee if callee in scans else None
            return None
        if ref[0] == "name":
            cands = module_fns.get(ref[1], [])
            if len(cands) == 1:
                return cands[0]
        return None

    # caller graph (interprocedural, over the resolved call shapes)
    callers: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {k: set() for k in scans}
    for key, s in scans.items():
        for ref, _ in s.calls:
            callee = resolve(key, ref)
            if callee is not None and callee != key:
                callers[callee].add(key)

    def dominated(key: Tuple[str, str], seen: Set[Tuple[str, str]]) -> bool:
        """A function is dominated when it bumps itself, or when every
        caller is (recursively). No callers = a public entry that must
        bump itself. ``__init__`` callers count as dominated
        (construction precedes sharing)."""
        if key in seen:
            return False
        seen.add(key)
        s = scans.get(key)
        if s is not None and s.bumps:
            return True
        if key[1] in EXEMPT_METHODS:
            return True
        cs = callers.get(key, set())
        if not cs:
            return False
        return all(dominated(c, seen) for c in cs)

    allow = load_pair_allowlist(allowlist_path)
    seen_pairs: Set[Tuple[str, str]] = set()
    findings: List[Finding] = []
    emitted: Set[Tuple[str, str]] = set()  # (context, plane) dedup

    for key, s in scans.items():
        if not s.writes:
            continue
        if key[1] in EXEMPT_METHODS:
            continue
        if dominated(key, set()):
            continue
        relpath = scan_meta[key]
        ctx = f"{key[0]}.{key[1]}" if "." in key[0] else f"{key[0]}.{key[1]}"
        for plane, line in s.writes:
            seen_pairs.add((ctx, plane))
            if (ctx, plane) in allow:
                continue
            if (ctx, plane) in emitted:
                continue
            emitted.add((ctx, plane))
            short = ctx.rsplit(".", 2)
            findings.append(
                Finding(
                    checker="epochs",
                    path=relpath,
                    relpath=relpath,
                    line=line,
                    message=(
                        f"write to verdict-epoch plane '{plane}' not dominated "
                        f"by an epoch bump (in {'.'.join(short[-2:])})"
                    ),
                )
            )

    if stale_out is not None:
        stale_out.extend(sorted(p for p in allow if p not in seen_pairs))
    findings.sort(key=lambda f: (f.relpath, f.line, f.message))
    return findings
