"""Checker 11: retrace budgets — jit entries must see padded shapes.

The whole device story rests on *one compiled program per ladder rung*:
arrays are padded to pow2/capacity sizes (``_next_pow2``, the
``ensure_capacity`` ladders) so object churn never changes array shapes
and XLA never recompiles on the serving path (a recompile is a
100ms-class stall — the 2,311 ms TPU ticks in BENCH_TPU_LATEST are
dispatch/retrace-dominated). This checker pins the host→device boundary
shape discipline; the runtime half (``utils/retrace.py``,
``KT_JIT_RETRACE_BUDGET``) counts actual XLA compilations per entry and
fails a tick that recompiles after warmup.

Rules, per call site of a ``@jax.jit`` entry (entries discovered the
same way the purity checker finds them, call sites resolved through the
package import-alias index):

- **unpadded dynamic shape**: an argument that is (or names) a host
  allocation (``np.zeros``/``empty``/``full``/``ones``/``jnp.*``) whose
  shape expression is data-dependent — contains ``len(...)``,
  ``.shape``, ``.size``, or ``np.nonzero`` — without passing through a
  sanctioned padder (a ``*pow2*``/``*pad*`` call or a capacity-named
  value: ``*cap*``/``capacity``). Every distinct live count then
  compiles a fresh program;
- **data-dependent static arg**: a value bound to a ``static_argnames``
  parameter that is data-dependent by the same test. Static args key
  the compile cache by *value* — ``num_groups=len(groups)`` recompiles
  per distinct group count; ``num_groups=_next_pow2(len(groups))``
  amortizes onto the ladder.

Name resolution is one-hop flow-insensitive (``B = len(pods)`` taints
``B``; ``Bp = _next_pow2(B)`` launders it) — the committed padding
idiom is exactly one hop, and deeper flows belong to the runtime
counter.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, Module, iter_classes, iter_methods, unparse
from .purity import _decorator_jit_static, _FnIndex

_ALLOCATORS = {"zeros", "empty", "ones", "full"}
_DATADEP_CALLS = {"len", "nonzero", "count_nonzero", "flatnonzero"}
_DATADEP_ATTRS = {"shape", "size"}
_SANCTION_SUBSTRINGS = ("pow2", "pad", "cap", "ladder", "bucket")


def _entry_table(
    modules: Sequence[Module],
) -> Dict[Tuple[str, str], Tuple[List[str], Set[str]]]:
    """(modname, fn) -> (param names, static_argnames) for jit entries."""
    out: Dict[Tuple[str, str], Tuple[List[str], Set[str]]] = {}
    for m in modules:
        for node in ast.walk(m.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                res = _decorator_jit_static(dec)
                if res:
                    params = [
                        a.arg
                        for a in list(node.args.posonlyargs) + list(node.args.args)
                    ]
                    out[(m.modname, node.name)] = (params, res[1])
                    break
    return out


def _name_of(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class _Taint:
    """Flow-insensitive local env: name -> value expr (last assignment)."""

    def __init__(self, fn: ast.AST):
        self.env: Dict[str, ast.AST] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    self.env[t.id] = node.value

    def _scan(self, expr: ast.AST, pred, depth: int) -> bool:
        for sub in ast.walk(expr):
            if pred(sub):
                return True
            if depth > 0 and isinstance(sub, ast.Name) and sub.id in self.env:
                resolved = self.env[sub.id]
                if resolved is not expr and self._scan(resolved, pred, depth - 1):
                    return True
        return False

    def data_dependent(self, expr: ast.AST, depth: int = 2) -> bool:
        def pred(sub: ast.AST) -> bool:
            if isinstance(sub, ast.Call):
                n = _name_of(sub.func)
                if n in _DATADEP_CALLS:
                    return True
            if isinstance(sub, ast.Attribute) and sub.attr in _DATADEP_ATTRS:
                return True
            return False

        return self._scan(expr, pred, depth)

    def sanctioned(self, expr: ast.AST, depth: int = 2) -> bool:
        def pred(sub: ast.AST) -> bool:
            n = None
            if isinstance(sub, ast.Call):
                n = _name_of(sub.func)
            elif isinstance(sub, (ast.Name, ast.Attribute)):
                n = _name_of(sub)
            if n is None:
                return False
            low = n.lower()
            return any(s in low for s in _SANCTION_SUBSTRINGS)

        return self._scan(expr, pred, depth)


def _alloc_shape(call: ast.Call) -> Optional[ast.AST]:
    name = _name_of(call.func)
    if name in _ALLOCATORS and call.args:
        return call.args[0]
    return None


def check(modules: Sequence[Module]) -> List[Finding]:
    entries = _entry_table(modules)
    if not entries:
        return []
    index = _FnIndex(modules)
    by_name: Dict[str, List[Tuple[str, str]]] = {}
    for key in entries:
        by_name.setdefault(key[1], []).append(key)

    findings: List[Finding] = []

    def resolve(modname: str, call: ast.Call) -> Optional[Tuple[str, str]]:
        f = call.func
        if isinstance(f, ast.Name):
            r = index.resolve(modname, f.id)
            if r in entries:
                return r
            cands = by_name.get(f.id, [])
            return cands[0] if len(cands) == 1 else None
        if isinstance(f, ast.Attribute):
            cands = by_name.get(f.attr, [])
            return cands[0] if len(cands) == 1 else None
        return None

    def scan_function(module: Module, fn: ast.AST, where: str) -> None:
        taint = _Taint(fn)

        def check_traced_arg(expr: ast.AST, entry, pname, line: int) -> None:
            """An array arg: flag if it is/names an allocation with an
            unpadded data-dependent shape."""
            alloc = None
            if isinstance(expr, ast.Call):
                alloc = _alloc_shape(expr)
            elif isinstance(expr, ast.Name) and expr.id in taint.env:
                v = taint.env[expr.id]
                if isinstance(v, ast.Call):
                    alloc = _alloc_shape(v)
            if alloc is None:
                return
            if taint.data_dependent(alloc) and not taint.sanctioned(alloc):
                findings.append(
                    Finding(
                        checker="retrace",
                        path=module.path,
                        relpath=module.relpath,
                        line=line,
                        message=(
                            f"arg '{pname}' of jit entry {entry[0]}.{entry[1]} "
                            f"is allocated with a data-dependent shape "
                            f"({unparse(alloc)}) in {where} — every distinct "
                            "size recompiles; route through _next_pow2/"
                            "capacity padding"
                        ),
                    )
                )

        def check_static_arg(expr: ast.AST, entry, pname, line: int) -> None:
            if taint.data_dependent(expr) and not taint.sanctioned(expr):
                findings.append(
                    Finding(
                        checker="retrace",
                        path=module.path,
                        relpath=module.relpath,
                        line=line,
                        message=(
                            f"static arg '{pname}' of jit entry "
                            f"{entry[0]}.{entry[1]} is data-dependent "
                            f"({unparse(expr)}) in {where} — static args key "
                            "the compile cache by value; pad to the ladder "
                            "first"
                        ),
                    )
                )

        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            entry = resolve(module.modname, node)
            if entry is None:
                continue
            params, static = entries[entry]
            for i, a in enumerate(node.args):
                pname = params[i] if i < len(params) else f"arg{i}"
                if pname in static:
                    check_static_arg(a, entry, pname, node.lineno)
                else:
                    check_traced_arg(a, entry, pname, node.lineno)
            for kw in node.keywords:
                if kw.arg is None:
                    continue
                if kw.arg in static:
                    check_static_arg(kw.value, entry, kw.arg, node.lineno)
                else:
                    check_traced_arg(kw.value, entry, kw.arg, node.lineno)

    for m in modules:
        for node in m.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if (m.modname, node.name) in entries:
                    continue
                scan_function(m, node, f"{m.modname}.{node.name}")
        for cls in iter_classes(m):
            for method in iter_methods(cls):
                scan_function(m, method, f"{m.modname}.{cls.name}.{method.name}")

    uniq = {}
    for f in findings:
        uniq.setdefault((f.key(), f.line), f)
    return sorted(uniq.values(), key=lambda f: (f.relpath, f.line))
