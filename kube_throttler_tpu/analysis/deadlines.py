"""Checker 14 (gen-4): deadline discipline on the sharding/replication
transports.

PR 16's partition-tolerance contract: every blocking operation on the
cross-host transport carries a deadline — a scatter RPC that outruns
its budget degrades fail-safe instead of blocking admission, a dial
that hangs is cut by ``connect_timeout``, shutdown joins are bounded.
A single unbounded ``recv``/``connect``/``wait`` reached from the
transport re-introduces the head-of-line hang the whole discipline
exists to prevent, and nothing crashes until a partition day.

The checker scans every function defined in the transport scope
(``sharding/`` and ``engine/replication.py``) plus every function
reachable from one — interprocedurally to fixpoint over the same call
shapes the blocking checker resolves (``self.m()``, ``self.attr.m()``
with one level of attribute-type inference, unique bare-name module
functions) — and flags the deadline-less shapes:

- ``X.wait()`` with no timeout (Event/Condition/future slots — the RPC
  waiter side) or an explicit ``timeout=None``;
- ``X.join()`` with no timeout on a thread-ish base (``",".join(xs)``
  always has an argument and a string base — not a thread join);
- ``X.result()`` with no timeout — a future wait on a scatter RPC must
  either pass one or be provably bounded by the task's own deadline
  (the vetted ``_scatter`` shape — allow-filed, not invisible);
- ``socket.create_connection(...)`` without a ``timeout=``;
- ``X.connect(...)`` with no prior ``X.settimeout(...)`` in the same
  function;
- ``X.recv(...)``/``X.recv_into(...)`` with no prior
  ``X.settimeout(...)`` in the same function (connection-lifetime
  reader threads in-tree read via the framed layer whose lifecycle is
  socket close — a raw deadline-less ``recv`` is a new ingestion
  point, not an idiom).

Vetted exceptions go in ``deadline_allow.txt``, one per line::

    sharding.front.Front._scatter -> .result()  # bounded by the per-op RPC deadline inside the task

keyed ``(context, descriptor)`` with a mandatory justification; stale
entries FAIL the run (``--prune-stale`` deletes them).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, Module, iter_classes, iter_methods, load_pair_allowlist

_SCOPE_PREFIXES = ("sharding/",)
_SCOPE_FILES = ("engine/replication.py",)


def in_scope(module: Module) -> bool:
    rel = module.relpath.replace("\\", "/")
    return rel.startswith(_SCOPE_PREFIXES) or rel in _SCOPE_FILES


def _no_timeout(call: ast.Call) -> bool:
    """True when the call passes no bound: no args/kwargs, or an
    explicit ``timeout=None`` / first-positional ``None``."""
    if not call.args and not call.keywords:
        return True
    if call.args and isinstance(call.args[0], ast.Constant) and call.args[0].value is None:
        return True
    for k in call.keywords:
        if k.arg == "timeout" and isinstance(k.value, ast.Constant) and k.value.value is None:
            return True
    return False


class _FnScan:
    """One function's deadline-less ops and call refs."""

    def __init__(self) -> None:
        self.ops: List[Tuple[str, int]] = []  # (descriptor, line)
        self.calls: List[Tuple[str, ...]] = []  # resolution refs


def _scan_function(fn: ast.AST, out: _FnScan) -> None:
    from .core import unparse

    # bases settimeout() was called on, in lexical order of appearance
    timed_bases: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute):
            base_txt = unparse(f.value)
            if f.attr == "settimeout":
                if not (
                    node.args
                    and isinstance(node.args[0], ast.Constant)
                    and node.args[0].value is None
                ):
                    timed_bases.add(base_txt)
                continue
            if f.attr == "wait" and _no_timeout(node):
                out.ops.append((".wait()", node.lineno))
            elif f.attr == "result" and _no_timeout(node):
                out.ops.append((".result()", node.lineno))
            elif (
                f.attr == "join"
                and not node.args
                and not node.keywords
                and not (isinstance(f.value, ast.Constant) and isinstance(f.value.value, str))
            ):
                out.ops.append((".join()", node.lineno))
            elif f.attr == "create_connection":
                if not any(k.arg == "timeout" for k in node.keywords) and len(node.args) < 2:
                    out.ops.append(("create_connection()", node.lineno))
            elif f.attr == "connect":
                if base_txt not in timed_bases:
                    out.ops.append((".connect()", node.lineno))
            elif f.attr in ("recv", "recv_into"):
                if base_txt not in timed_bases:
                    out.ops.append((f".{f.attr}()", node.lineno))
            # call refs for reachability
            base = f.value
            if isinstance(base, ast.Name) and base.id == "self":
                out.calls.append(("self", f.attr))
            elif (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
            ):
                out.calls.append(("attr", base.attr, f.attr))
        elif isinstance(f, ast.Name):
            if f.id == "create_connection":
                if not any(k.arg == "timeout" for k in node.keywords) and len(node.args) < 2:
                    out.ops.append(("create_connection()", node.lineno))
            out.calls.append(("name", f.id))


def check(
    modules: Sequence[Module],
    allowlist_path: Optional[str] = None,
    stale_out: Optional[List[Tuple[str, str]]] = None,
) -> List[Finding]:
    from .lockgraph import _ClassInfo, _collect_class_info

    classes: Dict[str, _ClassInfo] = {}
    by_bare_name: Dict[str, List[_ClassInfo]] = {}
    for m in modules:
        for cls in iter_classes(m):
            info = _collect_class_info(m, cls)
            classes[info.qual] = info
            by_bare_name.setdefault(cls.name, []).append(info)

    scans: Dict[Tuple[str, str], _FnScan] = {}
    scan_meta: Dict[Tuple[str, str], str] = {}
    module_fns: Dict[str, List[Tuple[str, str]]] = {}
    entries: Set[Tuple[str, str]] = set()  # transport-scope roots
    for m in modules:
        method_ids = set()
        for cls in iter_classes(m):
            qual = f"{m.modname}.{cls.name}"
            for method in iter_methods(cls):
                method_ids.add(id(method))
                s = _FnScan()
                _scan_function(method, s)
                scans[(qual, method.name)] = s
                scan_meta[(qual, method.name)] = m.relpath
                if in_scope(m):
                    entries.add((qual, method.name))
        for node in m.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if id(node) in method_ids:
                    continue
                s = _FnScan()
                _scan_function(node, s)
                key = (m.modname, node.name)
                scans[key] = s
                scan_meta[key] = m.relpath
                module_fns.setdefault(node.name, []).append(key)
                if in_scope(m):
                    entries.add(key)

    def resolve(key: Tuple[str, str], ref: Tuple[str, ...]) -> Optional[Tuple[str, str]]:
        owner, _ = key
        if ref[0] == "self":
            callee = (owner, ref[1])
            return callee if callee in scans else None
        if ref[0] == "attr":
            info = classes.get(owner)
            if info is None:
                return None
            tname = info.attr_types.get(ref[1])
            if tname is None:
                return None
            cands = by_bare_name.get(tname, [])
            if len(cands) == 1:
                callee = (cands[0].qual, ref[2])
                return callee if callee in scans else None
            return None
        if ref[0] == "name":
            cands = module_fns.get(ref[1], [])
            if len(cands) == 1:
                return cands[0]
        return None

    # reachability closure from the transport-scope roots
    reachable: Set[Tuple[str, str]] = set(entries)
    frontier = list(entries)
    while frontier:
        key = frontier.pop()
        for ref in scans[key].calls:
            callee = resolve(key, ref)
            if callee is not None and callee not in reachable:
                reachable.add(callee)
                frontier.append(callee)

    allow = load_pair_allowlist(allowlist_path)
    seen_pairs: Set[Tuple[str, str]] = set()
    findings: List[Finding] = []
    emitted: Set[Tuple[str, str]] = set()

    for key in sorted(reachable):
        s = scans[key]
        if not s.ops:
            continue
        ctx = f"{key[0]}.{key[1]}"
        relpath = scan_meta[key]
        for desc, line in s.ops:
            seen_pairs.add((ctx, desc))
            if (ctx, desc) in allow:
                continue
            if (ctx, desc) in emitted:
                continue
            emitted.add((ctx, desc))
            short = ".".join(ctx.rsplit(".", 2)[-2:])
            findings.append(
                Finding(
                    checker="deadlines",
                    path=relpath,
                    relpath=relpath,
                    line=line,
                    message=f"deadline-less {desc} on the transport path (in {short})",
                )
            )

    if stale_out is not None:
        stale_out.extend(sorted(p for p in allow if p not in seen_pairs))
    findings.sort(key=lambda f: (f.relpath, f.line, f.message))
    return findings
