"""Checker 9: dtype/overflow discipline over the int64 milli-unit planes.

``ops/schema.py`` declares the int64 planes as a literal set
(``INT64_MILLI_PLANES`` — read from the AST here, the same registry
idiom as fault sites and metric families). Those tensors carry exact
milli-unit quantities and pod counts summed over up to 1M pods; an
int32 accumulator overflows at ~2.1 cores across 1k pods and a float
cast silently rounds. The checker scans ``ops/``, ``parallel/``, and
the engine staging planes (``engine/devicestate.py``,
``engine/columnar.py``) for three shapes:

- **narrowing cast** — ``<plane>.astype(jnp.int32)`` (or int16/8,
  uint*, float16/32/64, via ``astype``/``asarray``/``array`` with a
  narrow dtype) applied to an expression mentioning a declared plane.
  float64 counts as narrowing: milli values exceed 2^53. The vetted
  exact-float64 path (``ops/aggregate.py``) splits into 32-bit limbs
  under *different names* first, so it does not trip this rule;
- **narrow accumulator** — a reduction (``sum``/``cumsum``/``dot``/
  ``matmul``/``einsum``/``segment_sum``/``tensordot``/``prod``) whose
  ``dtype=`` is narrow while an operand mentions a declared plane
  (reductions over masks/statuses with int32 accumulators stay legal);
- **default-dtype allocation** — ``np.zeros``/``np.empty``/``np.ones``/
  ``np.full``/``jnp.zeros``/... assigned to a declared plane name
  without an explicit ``dtype=``: numpy defaults to float64 (and
  platform-C-long for ``full`` of ints), jnp defaults to float32 —
  either silently floats the milli math.

The rules are name-syntactic on purpose: the planes are *declared*, so
a rename without updating the declaration is caught by the default-
dtype/narrowing rules going silent on the new name while the stale
declaration keeps the honest writer honest (update the set in the same
commit). Interprocedural value flow is the runtime differential soaks'
job; this checker pins the declared boundary.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence, Set

from .core import Finding, Module, unparse

# dtypes that cannot hold an exact int64 milli value
NARROW_DTYPES = {
    "int8",
    "int16",
    "int32",
    "uint8",
    "uint16",
    "uint32",
    "float16",
    "bfloat16",
    "float32",
    "float64",
}

_CAST_CALLS = {"asarray", "array", "full", "full_like", "zeros_like", "ones_like"}
_REDUCTIONS = {
    "sum",
    "cumsum",
    "prod",
    "dot",
    "matmul",
    "einsum",
    "tensordot",
    "segment_sum",
}
_ALLOCATORS = {"zeros", "empty", "ones", "full", "zeros_like", "empty_like"}

_DEVICE_SCOPE_PREFIXES = ("ops/", "parallel/")
_DEVICE_SCOPE_FILES = ("engine/devicestate.py", "engine/columnar.py")

_FALLBACK_PLANES = frozenset(
    {"thr_cnt", "thr_req", "used_cnt", "used_req", "res_cnt", "res_req", "req", "pod_req"}
)


def in_scope(module: Module) -> bool:
    rel = module.relpath.replace("\\", "/")
    return rel.startswith(_DEVICE_SCOPE_PREFIXES) or rel in _DEVICE_SCOPE_FILES


def load_planes(modules: Sequence[Module]) -> Set[str]:
    """``INT64_MILLI_PLANES`` literal from ops/schema.py's AST; the
    checked-in fallback only applies when the declaring module is outside
    the analyzed root (fixture trees declare their own)."""
    for m in modules:
        if not m.relpath.replace("\\", "/").endswith("schema.py"):
            continue
        for node in m.tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
                value = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
                value = node.value
            else:
                continue
            for t in targets:
                if isinstance(t, ast.Name) and t.id == "INT64_MILLI_PLANES":
                    try:
                        got = ast.literal_eval(value)
                    except ValueError:
                        continue
                    return {str(v) for v in got}
    return set(_FALLBACK_PLANES)


def _dtype_name(node: ast.AST) -> Optional[str]:
    """'int32' for jnp.int32 / np.int32 / "int32" / int32."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _mentioned_planes(expr: ast.AST, planes: Set[str]) -> Set[str]:
    """Declared plane names appearing as identifiers/attributes in expr
    as *values*. ``*_present`` masks and ``st_*`` flags are distinct
    names, so they never collide; a plane inside a comparison
    (``req != 0``, ``pod_req > thr_req``) yields a bool mask, not milli
    values — casting THAT is legal, so Compare subtrees are skipped."""
    hits: Set[str] = set()

    def walk(node: ast.AST) -> None:
        if isinstance(node, ast.Compare):
            return
        if isinstance(node, ast.Name) and node.id in planes:
            hits.add(node.id)
            return
        if isinstance(node, ast.Attribute):
            if node.attr in planes:
                hits.add(node.attr)
            return
        for child in ast.iter_child_nodes(node):
            walk(child)

    walk(expr)
    return hits


def _call_attr(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _dtype_kwarg(call: ast.Call) -> Optional[str]:
    for kw in call.keywords:
        if kw.arg == "dtype":
            return _dtype_name(kw.value)
    return None


def _target_names(node: ast.AST) -> List[str]:
    """Assignment-target plane candidates: bare names, self-attrs, and
    subscripted bases (``self.pod_req[row] = ...`` targets pod_req)."""
    out: List[str] = []
    t = node
    while isinstance(t, ast.Subscript):
        t = t.value
    if isinstance(t, ast.Name):
        out.append(t.id)
    elif isinstance(t, ast.Attribute):
        out.append(t.attr)
    elif isinstance(t, ast.Tuple):
        for elt in t.elts:
            out.extend(_target_names(elt))
    return out


def check_module(module: Module, planes: Set[str]) -> List[Finding]:
    findings: List[Finding] = []

    def emit(line: int, message: str) -> None:
        findings.append(
            Finding(
                checker="dtype",
                path=module.path,
                relpath=module.relpath,
                line=line,
                message=message,
            )
        )

    for node in module.walk():
        if isinstance(node, ast.Call):
            name = _call_attr(node)
            # narrowing cast: <expr over plane>.astype(narrow) or
            # asarray/array(<plane expr>, dtype=narrow)
            if name == "astype" and isinstance(node.func, ast.Attribute):
                dt = None
                if node.args:
                    dt = _dtype_name(node.args[0])
                dt = dt or _dtype_kwarg(node)
                if dt in NARROW_DTYPES:
                    hit = _mentioned_planes(node.func.value, planes)
                    if hit:
                        emit(
                            node.lineno,
                            f"narrowing cast .astype({dt}) of int64 plane "
                            f"{'/'.join(sorted(hit))} (declared in "
                            "ops/schema.py INT64_MILLI_PLANES)",
                        )
                continue
            if name in _CAST_CALLS:
                dt = _dtype_kwarg(node)
                if dt is None and len(node.args) >= 2 and name in ("asarray", "array"):
                    dt = _dtype_name(node.args[1])
                if dt in NARROW_DTYPES:
                    hit: Set[str] = set()
                    for a in node.args[:1]:
                        hit |= _mentioned_planes(a, planes)
                    if hit:
                        emit(
                            node.lineno,
                            f"narrowing {name}(..., dtype={dt}) of int64 plane "
                            f"{'/'.join(sorted(hit))}",
                        )
                # fall through: full/zeros_like are also allocators below
            if name in _REDUCTIONS:
                dt = _dtype_kwarg(node)
                if dt in NARROW_DTYPES:
                    hit = set()
                    for a in node.args:
                        hit |= _mentioned_planes(a, planes)
                    if isinstance(node.func, ast.Attribute):
                        hit |= _mentioned_planes(node.func.value, planes)
                    if hit:
                        emit(
                            node.lineno,
                            f"reduction {name}(dtype={dt}) over int64 plane "
                            f"{'/'.join(sorted(hit))} — accumulator must stay "
                            "int64",
                        )
        elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = getattr(node, "value", None)
            if not isinstance(value, ast.Call):
                continue
            name = _call_attr(value)
            if name not in _ALLOCATORS:
                continue
            if _dtype_kwarg(value) is not None:
                continue
            if name in ("zeros_like", "empty_like"):
                continue  # inherits the source dtype
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                for tn in _target_names(t):
                    if tn in planes:
                        emit(
                            value.lineno,
                            f"default-dtype {name}() assigned to int64 plane "
                            f"'{tn}' — numpy defaults to float64, jnp to "
                            "float32; pass dtype=np.int64",
                        )
    return findings


def check(modules: Sequence[Module]) -> List[Finding]:
    planes = load_planes(modules)
    out: List[Finding] = []
    for m in modules:
        if in_scope(m):
            out.extend(check_module(m, planes))
    return out
