"""Checker 12: every numeric parse of a ``KT_*`` env knob needs a guard.

The ``_GATHER_CHUNK_ELEMS`` bug class (ADVICE round 5): a bare
``int(os.environ.get("KT_GATHER_CHUNK_ELEMS", ...))`` at import time
means one malformed override kills module import — or, on a serving
path, kills the daemon at the first tick that reads the knob. The
repo convention (``tpu_watch.py``'s ``KT_TUNNEL_PROBE_PORT`` guard,
``gchygiene.py``) is ``try: int(...) except ValueError: <default>``.

The rule: any ``int(...)``/``float(...)`` whose argument reads an
environment variable named ``KT_*`` (``os.environ.get``, ``os.getenv``,
``os.environ[...]``, or a bare ``environ``/``getenv`` import alias)
must sit inside a ``try`` whose handlers catch ``ValueError`` /
``TypeError`` / ``Exception``. ``environ[...]`` additionally wants
``KeyError`` coverage, but any of the accepted handlers at least keeps
a typo'd value from becoming a crash loop.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence

from .core import Finding, Module

_GUARD_EXCEPTIONS = {"ValueError", "TypeError", "Exception", "BaseException"}


def _env_key(call_or_sub: ast.AST) -> Optional[str]:
    """The literal env-var name if node reads an environment variable."""
    if isinstance(call_or_sub, ast.Call):
        f = call_or_sub.func
        fname = None
        if isinstance(f, ast.Attribute):
            # os.environ.get / os.getenv
            if f.attr in ("get", "getenv"):
                base = f.value
                base_txt = (
                    base.attr if isinstance(base, ast.Attribute)
                    else base.id if isinstance(base, ast.Name) else ""
                )
                if base_txt in ("environ", "os"):
                    fname = f.attr
        elif isinstance(f, ast.Name) and f.id == "getenv":
            fname = "getenv"
        if fname and call_or_sub.args:
            a = call_or_sub.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                return a.value
        return None
    if isinstance(call_or_sub, ast.Subscript):
        base = call_or_sub.value
        base_txt = (
            base.attr if isinstance(base, ast.Attribute)
            else base.id if isinstance(base, ast.Name) else ""
        )
        if base_txt == "environ":
            s = call_or_sub.slice
            if isinstance(s, ast.Constant) and isinstance(s.value, str):
                return s.value
    return None


def _handler_catches(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    names = []
    if isinstance(t, ast.Tuple):
        names = [e for e in t.elts]
    else:
        names = [t]
    for n in names:
        txt = n.attr if isinstance(n, ast.Attribute) else getattr(n, "id", "")
        if txt in _GUARD_EXCEPTIONS:
            return True
    return False


def check_module(module: Module) -> List[Finding]:
    findings: List[Finding] = []
    # guarded line ranges: bodies of try statements with an accepted handler
    guarded: List[tuple] = []
    for node in module.walk():
        if isinstance(node, ast.Try) and any(
            _handler_catches(h) for h in node.handlers
        ):
            start = node.lineno
            end = max(
                (getattr(s, "end_lineno", s.lineno) for s in node.body),
                default=node.lineno,
            )
            guarded.append((start, end))

    def is_guarded(line: int) -> bool:
        return any(a <= line <= b for a, b in guarded)

    for node in module.walk():
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Name) and f.id in ("int", "float")):
            continue
        key = None
        for sub in ast.walk(node):
            if sub is node:
                continue
            key = _env_key(sub)
            if key is not None:
                break
        if key is None or not key.startswith("KT_"):
            continue
        if is_guarded(node.lineno):
            continue
        findings.append(
            Finding(
                checker="envguard",
                path=module.path,
                relpath=module.relpath,
                line=node.lineno,
                message=(
                    f"unguarded {f.id}() parse of env knob '{key}' — a "
                    "malformed override becomes a crash; wrap in try/except "
                    "ValueError with the default as fallback"
                ),
            )
        )
    return findings


def check(modules: Sequence[Module]) -> List[Finding]:
    out: List[Finding] = []
    for m in modules:
        out.extend(check_module(m))
    return out
