"""Checker 8: cross-process protocol registries.

Three protocols cross a process (or crash) boundary in this codebase,
and each is keyed by short literal strings that no type system sees.
Drift is silent by construction — an unhandled control line is "skipped
corruption", an unhandled IPC frame is dropped on the floor, an unfenced
durable write is split-brain waiting for a pause. The checker pins each
registry's emit and dispatch sides against each other:

1. **Journal control lines.** Any dict literal ``{"type": "X", ...}``
   with an UPPERCASE type that is not a store watch-event type
   (ADDED/MODIFIED/DELETED/BOOKMARK/ERROR) is a journal control line
   (EPOCH, GANG, ...). Every emitted control type must be dispatched in
   ``StoreJournal._apply`` (local replay), dispatched in
   ``StandbyReplicator._apply_lines`` (the replication stream applies
   the same wire format — a control line the standby does not recognize
   is counted as corruption and its meaning is LOST on the standby), and
   re-emitted or explicitly handled in ``StoreJournal._compact_locked``
   (compaction rewrites the log from the store; control state not
   re-emitted is erased by every compaction).

2. **IPC frame message types.** ``send_frame(sock, lock, "mtype", ...)``
   literals partition by side — front (``sharding/ipc.py``,
   ``sharding/front.py``, ``sharding/supervisor.py``) vs worker
   (``sharding/worker.py``). Every mtype the front sends must be
   compared against a literal in the worker's dispatch (and vice versa),
   and every mtype a dispatch handles must have a sender somewhere —
   a handler nothing sends is dead protocol surface.

3. **Fencing-epoch domination.** In ``engine/journal.py`` and
   ``engine/snapshot.py``, any method of a fencing-aware class (one that
   assigns ``self.fencing``) that performs a durable write — a
   ``self._file.write``, an ``os.replace``, an ``os.fsync`` — must be
   *dominated* by an ``is_stale()`` check: either in its own body, or
   every in-class caller of the helper is itself dominated (a private
   writer funneled exclusively through checked entries is safe by
   construction; a method nobody in-class calls is a public entry and
   must check for itself). ``__init__``/``close`` are exempt
   (construction pre-dates leadership; shutdown flush must work fenced
   or not).

4. **Format registry coverage.** ``version.FORMAT_REGISTRY`` is the
   single source of truth mapping every durable/wire format to the
   minimum reader version that understands it — the rolling-upgrade
   contract. The registry must be a PURE dict literal (a computed
   registry cannot be audited at review time), and it must cover the
   code: every IPC frame mtype sent or dispatched needs a
   ``frame:<mtype>`` row, every emitted journal control type needs a
   ``journal:<TYPE>`` row, every entry of
   ``snapshot.SUPPORTED_SNAPSHOT_VERSIONS`` needs a ``snapshot:<v>``
   row, and every entry of ``shmring.SHM_FORMATS`` (the shared-memory
   event-ring layouts) needs an ``shm:<name>`` row. Stale rows (a
   registry entry whose referent no longer exists in
   the code) are findings too — a dead row misstates the compatibility
   surface to operators planning a roll.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, Module, iter_classes, iter_methods, literal_str, unparse

_EVENT_TYPES = {"ADDED", "MODIFIED", "DELETED", "BOOKMARK", "ERROR"}
_FRONT_FILES = ("sharding/ipc.py", "sharding/front.py", "sharding/supervisor.py")
_WORKER_FILES = ("sharding/worker.py",)
_FENCED_EXEMPT = {"__init__", "close", "__del__"}


def _norm(relpath: str) -> str:
    return relpath.replace("\\", "/")


def _find_function(
    modules: Sequence[Module], cls_name: str, fn_name: str
) -> Optional[Tuple[Module, ast.FunctionDef]]:
    for m in modules:
        for cls in iter_classes(m):
            if cls.name != cls_name:
                continue
            for meth in iter_methods(cls):
                if meth.name == fn_name:
                    return m, meth
    return None


def _string_constants(fn: ast.AST) -> Set[str]:
    return {
        node.value
        for node in ast.walk(fn)
        if isinstance(node, ast.Constant) and isinstance(node.value, str)
    }


def _check_control_lines(modules: Sequence[Module], findings: List[Finding]) -> None:
    # emitted control types: {"type": "X"} dict literals, X uppercase,
    # not a watch-event type, no "object" key (event lines carry objects)
    emitted: Dict[str, Tuple[str, int]] = {}
    for m in modules:
        for node in m.walk():
            if not isinstance(node, ast.Dict):
                continue
            ctype = None
            has_object = False
            for k, v in zip(node.keys, node.values):
                ks = literal_str(k) if k is not None else None
                if ks == "type":
                    vs = literal_str(v)
                    if vs and vs.isupper() and vs not in _EVENT_TYPES:
                        ctype = vs
                if ks == "object":
                    has_object = True
            if ctype and not has_object:
                emitted.setdefault(ctype, (m.relpath, node.lineno))

    venues = (
        ("StoreJournal", "_apply", "journal replay dispatch"),
        ("StandbyReplicator", "_apply_lines", "replication stream dispatch"),
        ("StoreJournal", "_compact_locked", "compaction re-emit"),
    )
    for cls_name, fn_name, what in venues:
        found = _find_function(modules, cls_name, fn_name)
        if found is None:
            continue  # fixture trees without the engine are fine
        vm, vfn = found
        known = _string_constants(vfn)
        for ctype, (relpath, line) in sorted(emitted.items()):
            if ctype not in known:
                findings.append(
                    Finding(
                        checker="protocol",
                        path=relpath,
                        relpath=relpath,
                        line=line,
                        message=(
                            f"journal control type '{ctype}' is emitted but "
                            f"absent from {cls_name}.{fn_name} ({what}) — "
                            "its meaning is silently lost there"
                        ),
                    )
                )


def _check_ipc_frames(modules: Sequence[Module], findings: List[Finding]) -> None:
    sends: Dict[str, List[Tuple[str, str, int]]] = {"front": [], "worker": []}
    handler_consts: Dict[str, Set[str]] = {"front": set(), "worker": set()}
    have_sharding = False
    for m in modules:
        rel = _norm(m.relpath)
        side = (
            "front" if rel.endswith(_FRONT_FILES)
            else "worker" if rel.endswith(_WORKER_FILES)
            else None
        )
        if side is None:
            continue
        have_sharding = True
        for node in m.walk():
            if isinstance(node, ast.Call):
                fname = (
                    node.func.id
                    if isinstance(node.func, ast.Name)
                    else getattr(node.func, "attr", None)
                )
                if fname == "send_frame" and len(node.args) >= 3:
                    mtype = literal_str(node.args[2])
                    if mtype is not None:
                        sends[side].append((mtype, m.relpath, node.lineno))
            elif isinstance(node, ast.Compare):
                # `mtype == "evt"` / `elif mtype == "req"` dispatch arms —
                # only comparisons against the frame-type variable count
                # (``fault.mode == "kill"`` and friends are not protocol)
                if not (
                    isinstance(node.left, ast.Name) and node.left.id == "mtype"
                ):
                    continue
                for comp in node.comparators:
                    s = literal_str(comp)
                    if s is not None:
                        handler_consts[side].add(s)
    if not have_sharding:
        return
    opposite = {"front": "worker", "worker": "front"}
    for side, entries in sends.items():
        for mtype, relpath, line in entries:
            if mtype not in handler_consts[opposite[side]]:
                findings.append(
                    Finding(
                        checker="protocol",
                        path=relpath,
                        relpath=relpath,
                        line=line,
                        message=(
                            f"IPC frame type '{mtype}' sent from the {side} "
                            f"side has no {opposite[side]}-side dispatch arm — "
                            "the frame is dropped on the floor"
                        ),
                    )
                )
    sent_types = {
        side: {mtype for mtype, _, _ in entries} for side, entries in sends.items()
    }
    for side, consts in handler_consts.items():
        for mtype in sorted(consts):
            if mtype not in sent_types[opposite[side]]:
                findings.append(
                    Finding(
                        checker="protocol",
                        path=_FRONT_FILES[0] if side == "front" else _WORKER_FILES[0],
                        relpath=_FRONT_FILES[0] if side == "front" else _WORKER_FILES[0],
                        line=1,
                        message=(
                            f"IPC dispatch arm for '{mtype}' on the {side} "
                            f"side has no {opposite[side]}-side sender — dead "
                            "protocol surface"
                        ),
                    )
                )


def _durable_write_lines(fn: ast.AST) -> List[int]:
    out: List[int] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not isinstance(f, ast.Attribute):
            continue
        text = unparse(f)
        if f.attr == "write" and text.startswith("self._file"):
            out.append(node.lineno)
        elif text in ("os.replace", "os.fsync"):
            out.append(node.lineno)
    return out


def _check_fencing(modules: Sequence[Module], findings: List[Finding]) -> None:
    for m in modules:
        rel = _norm(m.relpath)
        if not rel.endswith(("engine/journal.py", "engine/snapshot.py")):
            continue
        for cls in iter_classes(m):
            fencing_aware = any(
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Attribute)
                    and t.attr == "fencing"
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    for t in node.targets
                )
                for node in ast.walk(cls)
            )
            if not fencing_aware:
                continue
            methods = {meth.name: meth for meth in iter_methods(cls)}
            callers: Dict[str, Set[str]] = {name: set() for name in methods}
            for meth in iter_methods(cls):
                for node in ast.walk(meth):
                    if (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                        and node.func.attr in methods
                    ):
                        callers[node.func.attr].add(meth.name)

            def checks_inline(fn: ast.AST) -> bool:
                return any(
                    isinstance(node, ast.Attribute) and node.attr == "is_stale"
                    for node in ast.walk(fn)
                )

            def dominated(name: str, seen: frozenset) -> bool:
                """The check itself, or EVERY in-class caller dominated —
                a private helper funneled through checked entries is
                dominated by construction; a method nobody in-class calls
                is a public entry and must check for itself."""
                if name in seen:
                    return True  # recursion: judged by the other paths
                if checks_inline(methods[name]):
                    return True
                calling = callers.get(name, set())
                if not calling:
                    return False
                return all(dominated(c, seen | {name}) for c in calling)

            for meth in iter_methods(cls):
                if meth.name in _FENCED_EXEMPT:
                    continue
                lines = _durable_write_lines(meth)
                if not lines:
                    continue
                if not dominated(meth.name, frozenset()):
                    findings.append(
                        Finding(
                            checker="protocol",
                            path=m.relpath,
                            relpath=m.relpath,
                            line=lines[0],
                            message=(
                                f"durable write in {cls.name}.{meth.name} is "
                                "not dominated by a fencing-epoch check — a "
                                "fenced (stale) leader can still mutate "
                                "durable state here"
                            ),
                        )
                    )


def _emitted_control_types(
    modules: Sequence[Module],
) -> Dict[str, Tuple[str, int]]:
    """Journal control types emitted anywhere: ``{"type": "X"}`` dict
    literals with an uppercase non-watch-event type and no ``object``
    key (the scan _check_control_lines pins dispatch against)."""
    emitted: Dict[str, Tuple[str, int]] = {}
    for m in modules:
        for node in m.walk():
            if not isinstance(node, ast.Dict):
                continue
            ctype = None
            has_object = False
            for k, v in zip(node.keys, node.values):
                ks = literal_str(k) if k is not None else None
                if ks == "type":
                    vs = literal_str(v)
                    if vs and vs.isupper() and vs not in _EVENT_TYPES:
                        ctype = vs
                if ks == "object":
                    has_object = True
            if ctype and not has_object:
                emitted.setdefault(ctype, (m.relpath, node.lineno))
    return emitted


def _check_format_registry(modules: Sequence[Module], findings: List[Finding]) -> None:
    reg: Optional[Tuple[Module, ast.Assign]] = None
    for m in modules:
        if not _norm(m.relpath).endswith("version.py"):
            continue
        for node in m.walk():
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "FORMAT_REGISTRY"
                for t in node.targets
            ):
                reg = (m, node)
                break
    if reg is None:
        return  # fixture trees without version.py have no contract to pin
    vm, vnode = reg
    if not isinstance(vnode.value, ast.Dict):
        findings.append(
            Finding(
                checker="protocol",
                path=vm.relpath,
                relpath=vm.relpath,
                line=vnode.lineno,
                message=(
                    "FORMAT_REGISTRY must be a pure dict literal — a "
                    "computed registry cannot be audited at review time"
                ),
            )
        )
        return
    rows: Set[str] = set()
    for k in vnode.value.keys:
        ks = literal_str(k) if k is not None else None
        if ks is None:
            findings.append(
                Finding(
                    checker="protocol",
                    path=vm.relpath,
                    relpath=vm.relpath,
                    line=vnode.lineno,
                    message=(
                        "FORMAT_REGISTRY key is not a string literal — "
                        "the registry must be pure so the min-reader "
                        "contract is readable without executing code"
                    ),
                )
            )
            continue
        rows.add(ks)

    # frames: every mtype sent (send_frame literal) or dispatched
    # (`mtype == "..."`) on either side needs a frame:<mtype> row
    frame_uses: Dict[str, Tuple[str, int]] = {}
    have_sharding = False
    for m in modules:
        rel = _norm(m.relpath)
        if not rel.endswith(_FRONT_FILES + _WORKER_FILES):
            continue
        have_sharding = True
        for node in m.walk():
            if isinstance(node, ast.Call):
                fname = (
                    node.func.id
                    if isinstance(node.func, ast.Name)
                    else getattr(node.func, "attr", None)
                )
                if fname == "send_frame" and len(node.args) >= 3:
                    mtype = literal_str(node.args[2])
                    if mtype is not None:
                        frame_uses.setdefault(mtype, (m.relpath, node.lineno))
            elif isinstance(node, ast.Compare):
                if isinstance(node.left, ast.Name) and node.left.id == "mtype":
                    for comp in node.comparators:
                        s = literal_str(comp)
                        if s is not None:
                            frame_uses.setdefault(s, (m.relpath, node.lineno))
    for mtype, (relpath, line) in sorted(frame_uses.items()):
        if f"frame:{mtype}" not in rows:
            findings.append(
                Finding(
                    checker="protocol",
                    path=relpath,
                    relpath=relpath,
                    line=line,
                    message=(
                        f"IPC frame type '{mtype}' has no 'frame:{mtype}' "
                        "row in version.FORMAT_REGISTRY — its min-reader "
                        "contract is undeclared for rolling upgrades"
                    ),
                )
            )

    # journal control lines: every emitted type needs a journal:<TYPE> row
    emitted = _emitted_control_types(modules)
    have_journal = any(
        _norm(m.relpath).endswith("engine/journal.py") for m in modules
    )
    for ctype, (relpath, line) in sorted(emitted.items()):
        if f"journal:{ctype}" not in rows:
            findings.append(
                Finding(
                    checker="protocol",
                    path=relpath,
                    relpath=relpath,
                    line=line,
                    message=(
                        f"journal control type '{ctype}' has no "
                        f"'journal:{ctype}' row in version.FORMAT_REGISTRY "
                        "— replay cannot name the reader it requires"
                    ),
                )
            )

    # snapshot versions: every supported version needs a snapshot:<v> row
    snap_versions: Dict[str, Tuple[str, int]] = {}
    have_snapshot = False
    for m in modules:
        if not _norm(m.relpath).endswith("engine/snapshot.py"):
            continue
        have_snapshot = True
        for node in m.walk():
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "SUPPORTED_SNAPSHOT_VERSIONS"
                for t in node.targets
            ):
                if isinstance(node.value, (ast.Tuple, ast.List)):
                    for elt in node.value.elts:
                        if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, int
                        ):
                            snap_versions.setdefault(
                                str(elt.value), (m.relpath, node.lineno)
                            )
    for ver, (relpath, line) in sorted(snap_versions.items()):
        if f"snapshot:{ver}" not in rows:
            findings.append(
                Finding(
                    checker="protocol",
                    path=relpath,
                    relpath=relpath,
                    line=line,
                    message=(
                        f"supported snapshot version {ver} has no "
                        f"'snapshot:{ver}' row in version.FORMAT_REGISTRY "
                        "— its min-reader contract is undeclared"
                    ),
                )
            )

    # shm ring layouts: every entry of shmring.SHM_FORMATS needs an
    # shm:<name> row (same contract shape as snapshot versions — the
    # reader must be able to name the layout it requires)
    shm_formats: Dict[str, Tuple[str, int]] = {}
    have_shmring = False
    for m in modules:
        if not _norm(m.relpath).endswith("sharding/shmring.py"):
            continue
        have_shmring = True
        for node in m.walk():
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "SHM_FORMATS"
                for t in node.targets
            ):
                if isinstance(node.value, (ast.Tuple, ast.List)):
                    for elt in node.value.elts:
                        if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str
                        ):
                            shm_formats.setdefault(
                                elt.value, (m.relpath, node.lineno)
                            )
    for name, (relpath, line) in sorted(shm_formats.items()):
        if f"shm:{name}" not in rows:
            findings.append(
                Finding(
                    checker="protocol",
                    path=relpath,
                    relpath=relpath,
                    line=line,
                    message=(
                        f"shm ring format '{name}' has no "
                        f"'shm:{name}' row in version.FORMAT_REGISTRY "
                        "— its min-reader contract is undeclared"
                    ),
                )
            )

    # stale rows: a registry entry whose referent no longer exists
    # misstates the compatibility surface (only judged for domains whose
    # source of truth is present in the tree)
    for row in sorted(rows):
        domain, _, name = row.partition(":")
        stale = (
            (domain == "frame" and have_sharding and name not in frame_uses)
            or (domain == "journal" and have_journal and name not in emitted)
            or (domain == "snapshot" and have_snapshot and name not in snap_versions)
            or (domain == "shm" and have_shmring and name not in shm_formats)
        )
        unknown = domain not in ("frame", "journal", "snapshot", "shm")
        if stale or unknown:
            findings.append(
                Finding(
                    checker="protocol",
                    path=vm.relpath,
                    relpath=vm.relpath,
                    line=vnode.lineno,
                    message=(
                        f"FORMAT_REGISTRY row '{row}' is "
                        + (
                            "in an unknown domain "
                        "(expected frame:/journal:/snapshot:/shm:)"
                            if unknown
                            else "stale — nothing in the code emits or supports it"
                        )
                    ),
                )
            )


def check(modules: Sequence[Module]) -> List[Finding]:
    findings: List[Finding] = []
    _check_control_lines(modules, findings)
    _check_ipc_frames(modules, findings)
    _check_fencing(modules, findings)
    _check_format_registry(modules, findings)
    findings.sort(key=lambda f: (f.relpath, f.line, f.message))
    return findings
