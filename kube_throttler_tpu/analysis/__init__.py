"""Repo-native static analyzer: lock discipline, JAX trace purity, and
string-keyed registry consistency.

Run as ``python -m kube_throttler_tpu.analysis`` (or ``make lint``).
Checkers:

- ``guarded``   — guarded-by attribute discipline (guarded.py)
- ``lockorder`` — static lock-acquisition order graph (lockgraph.py)
- ``purity``    — JAX trace purity over ops/ and parallel/ (purity.py)
- ``registry``  — fault-site and metric-name registries (registry.py)

The runtime counterpart — the instrumented-lock assassin enabled by
``KT_LOCK_ASSERT=1`` — lives in ``kube_throttler_tpu.utils.lockorder``.
See docs/STATIC_ANALYSIS.md.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from . import guarded, lockgraph, purity, registry
from .core import Finding, Module, apply_baseline, load_baseline, load_package

PACKAGE_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.txt")
DEFAULT_ALLOWLIST = os.path.join(os.path.dirname(__file__), "lockorder_allow.txt")

CHECKERS = ("guarded", "lockorder", "purity", "registry")


def run_checks(
    modules: Sequence[Module],
    checks: Sequence[str] = CHECKERS,
    allowlist_path: Optional[str] = DEFAULT_ALLOWLIST,
) -> List[Finding]:
    findings: List[Finding] = []
    if "guarded" in checks:
        findings.extend(guarded.check(modules))
    if "lockorder" in checks:
        findings.extend(lockgraph.check(modules, allowlist_path=allowlist_path))
    if "purity" in checks:
        findings.extend(purity.check(modules))
    if "registry" in checks:
        findings.extend(registry.check(modules))
    findings.sort(key=lambda f: (f.relpath or f.path, f.line, f.checker, f.message))
    return findings


def run_repo(
    root: str = PACKAGE_ROOT,
    checks: Sequence[str] = CHECKERS,
    baseline_path: Optional[str] = DEFAULT_BASELINE,
    allowlist_path: Optional[str] = DEFAULT_ALLOWLIST,
):
    """(new, waived, stale) findings for the package at ``root``."""
    modules = load_package(root)
    findings = run_checks(modules, checks, allowlist_path)
    baseline = load_baseline(baseline_path) if baseline_path else {}
    return apply_baseline(findings, baseline)
