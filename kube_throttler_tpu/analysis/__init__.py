"""Repo-native static analyzer: lock discipline, JAX trace purity,
string-keyed registry consistency, (second generation) blocking-
under-lock, thread-lifecycle, exception-safety, cross-process protocol
checking, (third generation) device-kernel contract checking, and
(fourth generation) verdict-epoch coherence, transport deadline
discipline, and trust-boundary taint checking.

Run as ``python -m kube_throttler_tpu.analysis`` (or ``make lint``).
Checkers:

- ``guarded``   — guarded-by attribute discipline (guarded.py)
- ``lockorder`` — static lock-acquisition order graph (lockgraph.py)
- ``purity``    — JAX trace purity over ops/, parallel/, sharding/ (purity.py)
- ``registry``  — fault-site and metric-name registries (registry.py)
- ``blocking``  — blocking calls reached under a named lock (blocking.py)
- ``threads``   — silent thread death / daemon-under-lock / unbounded
  shutdown joins (threads.py)
- ``excsafety`` — fd/lock/reservation leaks on exception paths (excsafety.py)
- ``protocol``  — journal control lines, IPC frame types, fencing-epoch
  domination (protocol.py)
- ``dtype``     — int64 milli-plane dtype discipline: narrowing casts,
  narrow accumulators, default-dtype allocations (device.py)
- ``donation``  — no reads after a ``donate_argnums`` dispatch (donation.py)
- ``retrace``   — jit entries see only padded/static shapes (retrace.py)
- ``envguard``  — numeric ``KT_*`` env parses need try/except guards
  (envguard.py)
- ``epochs``    — (fourth generation) verdict-epoch coherence: every
  write to a declared verdict-affecting plane is dominated by an epoch
  bump (epochs.py)
- ``deadlines`` — blocking socket/RPC ops reached from the
  sharding/replication transports carry a timeout (deadlines.py)
- ``taint``     — trust-boundary taint: network bytes pass the
  ``hmac.compare_digest`` gate before ``pickle.loads``/``json.loads``
  (taint.py)

The runtime counterparts — the instrumented-lock assassin and hold-time
budgets (``KT_LOCK_ASSERT=1``, ``utils/lockorder.py``), the Eraser-style
lockset race detector (``KT_RACE_DETECT=1``, ``utils/racedetect.py``),
the per-entry XLA recompile budget (``KT_JIT_RETRACE_BUDGET``,
``utils/retrace.py``), and the verdict-coherence assassin
(``KT_EPOCH_ASSERT=1``, ``utils/epochassert.py``). See
docs/STATIC_ANALYSIS.md.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from . import (
    blocking,
    deadlines,
    device,
    donation,
    envguard,
    epochs,
    excsafety,
    guarded,
    lockgraph,
    protocol,
    purity,
    registry,
    retrace,
    taint,
    threads,
)
from .core import Finding, Module, apply_baseline, load_baseline, load_package

PACKAGE_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.txt")
DEFAULT_ALLOWLIST = os.path.join(os.path.dirname(__file__), "lockorder_allow.txt")
DEFAULT_BLOCKING_ALLOWLIST = os.path.join(
    os.path.dirname(__file__), "blocking_allow.txt"
)
DEFAULT_EPOCH_ALLOWLIST = os.path.join(os.path.dirname(__file__), "epoch_allow.txt")
DEFAULT_DEADLINE_ALLOWLIST = os.path.join(
    os.path.dirname(__file__), "deadline_allow.txt"
)

CHECKERS = (
    "guarded",
    "lockorder",
    "purity",
    "registry",
    "blocking",
    "threads",
    "excsafety",
    "protocol",
    "dtype",
    "donation",
    "retrace",
    "envguard",
    "epochs",
    "deadlines",
    "taint",
)


def run_checks(
    modules: Sequence[Module],
    checks: Sequence[str] = CHECKERS,
    allowlist_path: Optional[str] = DEFAULT_ALLOWLIST,
    blocking_allowlist_path: Optional[str] = DEFAULT_BLOCKING_ALLOWLIST,
    epoch_allowlist_path: Optional[str] = DEFAULT_EPOCH_ALLOWLIST,
    deadline_allowlist_path: Optional[str] = DEFAULT_DEADLINE_ALLOWLIST,
    stale_allow_out: Optional[Dict[str, List[Tuple[str, str]]]] = None,
) -> List[Finding]:
    """Run the selected checkers over ``modules``. ``stale_allow_out``
    (when given) maps checker name -> dead allowlist pairs so the CLI can
    error on (and ``--prune-stale``) waiver rot."""
    findings: List[Finding] = []
    if "guarded" in checks:
        findings.extend(guarded.check(modules))
    if "lockorder" in checks:
        stale: Optional[List[Tuple[str, str]]] = (
            stale_allow_out.setdefault("lockorder", [])
            if stale_allow_out is not None
            else None
        )
        findings.extend(
            lockgraph.check(modules, allowlist_path=allowlist_path, stale_out=stale)
        )
    if "purity" in checks:
        findings.extend(purity.check(modules))
    if "registry" in checks:
        findings.extend(registry.check(modules))
    if "blocking" in checks:
        stale = (
            stale_allow_out.setdefault("blocking", [])
            if stale_allow_out is not None
            else None
        )
        findings.extend(
            blocking.check(
                modules,
                allowlist_path=blocking_allowlist_path,
                stale_out=stale,
            )
        )
    if "threads" in checks:
        findings.extend(threads.check(modules))
    if "excsafety" in checks:
        findings.extend(excsafety.check(modules))
    if "protocol" in checks:
        findings.extend(protocol.check(modules))
    if "dtype" in checks:
        findings.extend(device.check(modules))
    if "donation" in checks:
        findings.extend(donation.check(modules))
    if "retrace" in checks:
        findings.extend(retrace.check(modules))
    if "envguard" in checks:
        findings.extend(envguard.check(modules))
    if "epochs" in checks:
        stale = (
            stale_allow_out.setdefault("epochs", [])
            if stale_allow_out is not None
            else None
        )
        findings.extend(
            epochs.check(modules, allowlist_path=epoch_allowlist_path, stale_out=stale)
        )
    if "deadlines" in checks:
        stale = (
            stale_allow_out.setdefault("deadlines", [])
            if stale_allow_out is not None
            else None
        )
        findings.extend(
            deadlines.check(
                modules, allowlist_path=deadline_allowlist_path, stale_out=stale
            )
        )
    if "taint" in checks:
        findings.extend(taint.check(modules))
    findings.sort(key=lambda f: (f.relpath or f.path, f.line, f.checker, f.message))
    return findings


def run_repo(
    root: str = PACKAGE_ROOT,
    checks: Sequence[str] = CHECKERS,
    baseline_path: Optional[str] = DEFAULT_BASELINE,
    allowlist_path: Optional[str] = DEFAULT_ALLOWLIST,
    blocking_allowlist_path: Optional[str] = DEFAULT_BLOCKING_ALLOWLIST,
    epoch_allowlist_path: Optional[str] = DEFAULT_EPOCH_ALLOWLIST,
    deadline_allowlist_path: Optional[str] = DEFAULT_DEADLINE_ALLOWLIST,
    stale_allow_out: Optional[Dict[str, List[Tuple[str, str]]]] = None,
):
    """(new, waived, stale) findings for the package at ``root``."""
    modules = load_package(root)
    findings = run_checks(
        modules,
        checks,
        allowlist_path,
        blocking_allowlist_path=blocking_allowlist_path,
        epoch_allowlist_path=epoch_allowlist_path,
        deadline_allowlist_path=deadline_allowlist_path,
        stale_allow_out=stale_allow_out,
    )
    baseline = load_baseline(baseline_path) if baseline_path else {}
    return apply_baseline(findings, baseline)
