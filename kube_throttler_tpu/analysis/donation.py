"""Checker 10: donation safety — no reads after a donated dispatch.

ROADMAP item 2 keeps the ``st_*`` planes device-resident across ticks
with **donated buffers** (``jax.jit(..., donate_argnums=...)``): XLA
reuses the input buffer for the output, so the Python-side array the
caller passed is *invalid* the moment the dispatch returns. Reading it
afterwards is not an error JAX reliably raises on every backend — on
TPU it can return garbage from the reused buffer. This checker pins the
contract before those kernels land:

- entries are functions whose ``jax.jit`` decoration carries
  ``donate_argnums``/``donate_argnames`` (positions resolved against
  the def's parameter list);
- call sites are resolved interprocedurally the same way the lockgraph
  resolver charges lock sets: bare-name calls via the package-wide
  import-alias index, ``self.<attr>.<fn>``/``obj.<fn>`` method calls via
  one level of attribute-type inference;
- at each call site, every argument expression bound to a donated
  parameter (a local name or a ``self.<attr>`` chain) is tracked through
  the *rest of the calling function*: a read at a later line with no
  intervening rebind of that name/attr is a finding. Rebinding — most
  idiomatically ``x = entry(x)``, the donate-and-replace shape — clears
  the obligation.

Line-granular and flow-approximate by design (a rebind anywhere between
the call line and the read line clears it, whichever branch it sits
in); the differential soaks catch value-level misuse, this catches the
structural use-after-donate the type checker never will.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, Module, iter_classes, iter_methods, unparse
from .purity import _FnIndex


def _donated_params(fn: ast.FunctionDef, dec: ast.Call) -> Set[str]:
    """Parameter names donated by a ``jax.jit``/``partial(jax.jit, ...)``
    decoration carrying donate_argnums/donate_argnames."""
    params = [a.arg for a in list(fn.args.posonlyargs) + list(fn.args.args)]
    out: Set[str] = set()
    for kw in dec.keywords:
        if kw.arg not in ("donate_argnums", "donate_argnames"):
            continue
        try:
            val = ast.literal_eval(kw.value)
        except ValueError:
            continue
        if isinstance(val, (int, str)):
            val = (val,)
        for v in val:
            if isinstance(v, int):
                if 0 <= v < len(params):
                    out.add(params[v])
            else:
                out.add(str(v))
    return out


def _param_names(fn: ast.FunctionDef) -> List[str]:
    return [a.arg for a in list(fn.args.posonlyargs) + list(fn.args.args)]


def _jit_donations(
    modules: Sequence[Module],
) -> Dict[Tuple[str, str], Tuple[Set[str], List[str]]]:
    """(modname, fn name) -> (donated param names, full param list), for
    every def whose decorator stack applies jax.jit with donation. Also
    resolves the ``g = jax.jit(f, donate_argnums=...)`` wrapper-
    assignment shape (the alias name becomes the entry, carrying the
    wrapped def's parameter list)."""
    out: Dict[Tuple[str, str], Tuple[Set[str], List[str]]] = {}
    for m in modules:
        defs: Dict[str, ast.FunctionDef] = {}
        for node in ast.walk(m.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, node)
                for dec in node.decorator_list:
                    if not isinstance(dec, ast.Call):
                        continue
                    if "jit" not in unparse(dec.func) and not any(
                        "jit" in unparse(a) for a in dec.args
                    ):
                        continue
                    donated = _donated_params(node, dec)
                    if donated:
                        out[(m.modname, node.name)] = (donated, _param_names(node))
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
                continue
            call = node.value
            if "jit" not in unparse(call.func):
                continue
            if not call.args:
                continue
            inner = call.args[0]
            if not (isinstance(inner, ast.Name) and inner.id in defs):
                continue
            donated_kw = [
                kw for kw in call.keywords
                if kw.arg in ("donate_argnums", "donate_argnames")
            ]
            if not donated_kw:
                continue
            wrapped = defs[inner.id]
            donated = _donated_params(wrapped, call)
            if donated:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[(m.modname, t.id)] = (donated, _param_names(wrapped))
    return out


def _arg_track_key(expr: ast.AST) -> Optional[str]:
    """Trackable donated-argument expression: 'x' for a bare name,
    'self.x' / 'obj.x' for a one-level attribute chain. Anything more
    complex (a fresh call result, a subscript) has no caller-side alias
    to misread, so it is not tracked."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        return f"{expr.value.id}.{expr.attr}"
    return None


def _expr_keys(expr: ast.AST) -> Set[str]:
    """Every trackable name/attr-chain read inside expr."""
    out: Set[str] = set()
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Attribute) and isinstance(sub.value, ast.Name):
            if isinstance(sub.ctx, ast.Load):
                out.add(f"{sub.value.id}.{sub.attr}")
        elif isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
            out.add(sub.id)
    return out


def _store_lines(fn: ast.AST) -> Dict[str, List[int]]:
    """key -> lines where the name/attr-chain is (re)bound."""
    out: Dict[str, List[int]] = {}

    def note(target: ast.AST, line: int) -> None:
        if isinstance(target, ast.Tuple):
            for elt in target.elts:
                note(elt, line)
            return
        key = _arg_track_key(target)
        if key is not None:
            out.setdefault(key, []).append(line)

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                note(t, node.lineno)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            note(node.target, node.lineno)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            note(node.target, node.lineno)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    note(item.optional_vars, node.lineno)
    return out


class _AttrTypes:
    """self-attribute → bare class name, per class (the lockgraph
    resolver's one-level attribute-type inference, reused so method-call
    sites on held sub-objects resolve the same way lock sets do)."""

    def __init__(self, modules: Sequence[Module]):
        from .lockgraph import _collect_class_info

        self.by_qual: Dict[str, object] = {}
        self.by_bare: Dict[str, List[object]] = {}
        for m in modules:
            for cls in iter_classes(m):
                info = _collect_class_info(m, cls)
                self.by_qual[info.qual] = info
                self.by_bare.setdefault(cls.name, []).append(info)


def check(modules: Sequence[Module]) -> List[Finding]:
    donations = _jit_donations(modules)
    if not donations:
        return []
    index = _FnIndex(modules)
    attr_types = _AttrTypes(modules)
    by_entry_name: Dict[str, List[Tuple[str, str]]] = {}
    for (mod, fn) in donations:
        by_entry_name.setdefault(fn, []).append((mod, fn))

    findings: List[Finding] = []

    def resolve_entry(
        modname: str, call: ast.Call, cls_info
    ) -> Optional[Tuple[str, str]]:
        f = call.func
        if isinstance(f, ast.Name):
            resolved = index.resolve(modname, f.id)
            if resolved in donations:
                return resolved
            cands = by_entry_name.get(f.id, [])
            return cands[0] if len(cands) == 1 else None
        if isinstance(f, ast.Attribute):
            # module alias (check.entry) or one-level attr-typed object
            cands = by_entry_name.get(f.attr, [])
            if len(cands) == 1:
                return cands[0]
        return None

    def scan_function(module: Module, fn: ast.AST, where: str, cls_info) -> None:
        stores = _store_lines(fn)
        obligations: List[Tuple[str, int, Tuple[str, str], str]] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            entry = resolve_entry(module.modname, node, cls_info)
            if entry is None:
                continue
            donated, params = donations[entry]
            bound: List[Tuple[str, ast.AST]] = []
            for i, a in enumerate(node.args):
                pname = params[i] if i < len(params) else None
                if pname in donated:
                    bound.append((pname, a))
            for kw in node.keywords:
                if kw.arg in donated:
                    bound.append((kw.arg, kw.value))
            for pname, a in bound:
                key = _arg_track_key(a)
                if key is not None:
                    line = getattr(node, "end_lineno", node.lineno)
                    obligations.append((key, line, entry, pname))
        if not obligations:
            return
        for node in ast.walk(fn):
            if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                getattr(node, "ctx", None), ast.Load
            ):
                key = _arg_track_key(node)
                if key is None:
                    continue
                for okey, oline, entry, pname in obligations:
                    if key != okey or node.lineno <= oline:
                        continue
                    rebound = any(
                        oline <= s <= node.lineno for s in stores.get(key, ())
                    )
                    if rebound:
                        continue
                    findings.append(
                        Finding(
                            checker="donation",
                            path=module.path,
                            relpath=module.relpath,
                            line=node.lineno,
                            message=(
                                f"read of '{key}' after it was donated (arg "
                                f"'{pname}' of {entry[0]}.{entry[1]}) in "
                                f"{where} — the buffer is reused by XLA; "
                                "rebind to the returned array or drop the "
                                "donation"
                            ),
                        )
                    )
                    break  # one finding per read site

    for m in modules:
        for node in m.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = (m.modname, node.name)
                if key in donations:
                    continue  # the entry's own body uses the fresh tracer
                scan_function(m, node, f"{m.modname}.{node.name}", None)
        for cls in iter_classes(m):
            info = attr_types.by_qual.get(f"{m.modname}.{cls.name}")
            for method in iter_methods(cls):
                scan_function(
                    m, method, f"{m.modname}.{cls.name}.{method.name}", info
                )
    # one finding per (key, obligation) pair is already enforced per read
    # site; collapse exact duplicates from nested walks
    uniq = {}
    for f in findings:
        uniq.setdefault((f.key(), f.line), f)
    return sorted(uniq.values(), key=lambda f: (f.relpath, f.line))
