"""Checker 2: static lock-acquisition order graph.

Builds the "acquired-while-holding" graph across the package:

- pass 1 discovers lock objects: ``self.X = threading.Lock()/RLock()``
  (also ``lockorder.make_lock``/``make_rlock`` factories, dict/list
  collections of locks, dataclass ``field(default_factory=threading.Lock)``,
  class- and module-level locks) and ``threading.Condition(self.Y)``
  aliases (acquiring the condition IS acquiring Y);
- pass 2 walks every function tracking the lexically-held set through
  ``with`` blocks; a nested acquisition adds edge ``outer -> inner``;
- call propagation: the lock set a method acquires transitively (through
  ``self.`` calls and one level of attribute-type inference from
  ``self.attr = ClassName(...)``) is charged against the held set at each
  call site, to fixpoint.

A cycle in the resulting graph is a potential lock inversion; vetted
orders are excluded via the allowlist file (``lockorder_allow.txt``,
lines ``nodeA -> nodeB  # reason``) which removes that edge before cycle
detection. Reentrant self-edges are reported only for non-reentrant
``threading.Lock`` nodes (an RLock may nest on itself).

Lock nodes are named ``<module>.<Class>.<attr>`` (or ``<module>.<attr>``
for module-level locks). Accessor methods that return a lock from a
collection (``def _key_lock(self): return self._key_locks[...]``) count
as acquiring the collection's node.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .core import Finding, Module, iter_classes, iter_methods, unparse

_LOCK_FACTORIES = ("Lock", "RLock", "make_lock", "make_rlock")
_REENTRANT_FACTORIES = ("RLock", "make_rlock")


def _factory_name(call: ast.Call) -> Optional[str]:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _is_lock_ctor(node: ast.AST) -> Optional[str]:
    """'lock' / 'rlock' if node constructs a lock, else None. Descends one
    level into list/dict/comprehension collections and dataclass field()."""
    if isinstance(node, ast.Call):
        name = _factory_name(node)
        if name in _LOCK_FACTORIES:
            return "rlock" if name in _REENTRANT_FACTORIES else "lock"
        if name == "field":
            for kw in node.keywords:
                if kw.arg == "default_factory":
                    v = kw.value
                    vname = (
                        v.attr if isinstance(v, ast.Attribute)
                        else v.id if isinstance(v, ast.Name) else None
                    )
                    if vname in _LOCK_FACTORIES:
                        return "rlock" if vname in _REENTRANT_FACTORIES else "lock"
        return None
    if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
        for elt in node.elts:
            k = _is_lock_ctor(elt)
            if k:
                return k
    if isinstance(node, ast.Dict):
        for v in node.values:
            k = _is_lock_ctor(v)
            if k:
                return k
    if isinstance(node, (ast.ListComp, ast.SetComp)):
        return _is_lock_ctor(node.elt)
    if isinstance(node, ast.DictComp):
        return _is_lock_ctor(node.value)
    return None


class _ClassInfo:
    def __init__(self, module: Module, cls: ast.ClassDef):
        self.module = module
        self.cls = cls
        self.qual = f"{module.modname}.{cls.name}"
        self.lock_attrs: Dict[str, str] = {}  # attr -> 'lock'|'rlock'
        self.cond_alias: Dict[str, Optional[str]] = {}  # cond attr -> lock attr
        self.accessor_alias: Dict[str, str] = {}  # method name -> lock attr
        self.attr_types: Dict[str, str] = {}  # attr -> bare class name

    def node_for_attr(self, attr: str) -> Optional[str]:
        if attr in self.lock_attrs:
            return f"{self.qual}.{attr}"
        if attr in self.cond_alias:
            target = self.cond_alias[attr]
            if target is not None and target in self.lock_attrs:
                return f"{self.qual}.{target}"
            return f"{self.qual}.{attr}"
        return None

    def reentrant(self, node: str) -> bool:
        attr = node.rsplit(".", 1)[-1]
        kind = self.lock_attrs.get(attr)
        if kind is None and attr in self.cond_alias:
            kind = "rlock"  # bare Condition owns an RLock
        return kind == "rlock"


def _ann_class_name(ann: ast.AST) -> Optional[str]:
    """Bare class name of an annotation: ``Store`` / ``Optional[Store]`` /
    ``"Store"`` (string annotation) -> 'Store'."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.Subscript):
        head = ann.value
        head_name = (
            head.id if isinstance(head, ast.Name)
            else head.attr if isinstance(head, ast.Attribute) else None
        )
        if head_name in ("Optional", "Union"):
            inner = ann.slice
            if isinstance(inner, ast.Tuple):
                for elt in inner.elts:
                    n = _ann_class_name(elt)
                    if n:
                        return n
                return None
            return _ann_class_name(inner)
        return None
    if isinstance(ann, ast.Name):
        return ann.id if ann.id[:1].isupper() else None
    if isinstance(ann, ast.Attribute):
        return ann.attr if ann.attr[:1].isupper() else None
    return None


def _attr_base_chain(expr: ast.AST) -> Optional[str]:
    """``self._agg_locks[k]`` / ``self._lock`` / ``cls._stats_lock`` /
    ``self._key_lock(key)`` -> the attribute name, else None."""
    node = expr
    if isinstance(node, ast.Call):
        node = node.func
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


# (id(module), id(cls)) -> _ClassInfo. Module objects are themselves
# memoized across checker runs (core._PARSE_CACHE), so identity is a
# stable key within one process: five checkers (lockorder, blocking,
# guarded, epochs, deadlines) walk the same class bodies — collecting
# once keeps full-repo `make lint` inside its 15s budget.
_CLASS_INFO_CACHE: dict = {}


def _collect_class_info(module: Module, cls: ast.ClassDef) -> _ClassInfo:
    key = (id(module), id(cls))
    cached = _CLASS_INFO_CACHE.get(key)
    if cached is not None and cached.module is module and cached.cls is cls:
        return cached
    info = _collect_class_info_uncached(module, cls)
    _CLASS_INFO_CACHE[key] = info
    return info


def _collect_class_info_uncached(module: Module, cls: ast.ClassDef) -> _ClassInfo:
    info = _ClassInfo(module, cls)
    # class-level lock attributes
    for node in cls.body:
        if isinstance(node, ast.Assign):
            kind = _is_lock_ctor(node.value)
            for t in node.targets:
                if isinstance(t, ast.Name):
                    if kind:
                        info.lock_attrs[t.id] = kind
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            kind = _is_lock_ctor(node.value)
            if kind and isinstance(node.target, ast.Name):
                info.lock_attrs[node.target.id] = kind
    # __init__ parameter annotations: ``self.store = store`` with
    # ``store: Store`` (or ``Optional[Store]``) types the attribute, so
    # ``with self.store._lock`` resolves to the Store's node instead of
    # being misread as this class's own ``_lock``
    param_types: Dict[str, str] = {}
    for method in iter_methods(cls):
        if method.name != "__init__":
            continue
        for a in list(method.args.args) + list(method.args.kwonlyargs):
            if a.annotation is not None:
                t = _ann_class_name(a.annotation)
                if t:
                    param_types[a.arg] = t
    # instance attributes, condition aliases, attr types
    for method in iter_methods(cls):
        for node in ast.walk(method):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if not (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    continue
                kind = _is_lock_ctor(node.value)
                if kind:
                    info.lock_attrs[t.attr] = kind
                    continue
                value = node.value
                if isinstance(value, ast.Name) and value.id in param_types:
                    info.attr_types[t.attr] = param_types[value.id]
                    continue
                if isinstance(value, ast.BoolOp):
                    # ``self.store = store or Store()``: either operand types it
                    for v in value.values:
                        if isinstance(v, ast.Name) and v.id in param_types:
                            info.attr_types[t.attr] = param_types[v.id]
                            break
                        if isinstance(v, ast.Call):
                            fname = _factory_name(v)
                            if fname and fname[0].isupper():
                                info.attr_types[t.attr] = fname
                                break
                    continue
                if isinstance(value, ast.Call):
                    fname = _factory_name(value)
                    if fname == "Condition":
                        target = None
                        if value.args:
                            target = _attr_base_chain(value.args[0])
                        info.cond_alias[t.attr] = target
                    elif fname and fname[0].isupper():
                        info.attr_types[t.attr] = fname
    # accessor methods returning a lock from a collection
    for method in iter_methods(cls):
        for node in ast.walk(method):
            if isinstance(node, ast.Return) and node.value is not None:
                attr = _attr_base_chain(node.value)
                if attr in info.lock_attrs:
                    info.accessor_alias[method.name] = attr
    return info


class _ModuleLocks:
    def __init__(self, module: Module):
        self.module = module
        self.names: Dict[str, str] = {}  # name -> 'lock'|'rlock'
        for node in module.tree.body:
            if isinstance(node, ast.Assign):
                kind = _is_lock_ctor(node.value)
                if kind:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.names[t.id] = kind


class _Graph:
    def __init__(self) -> None:
        # edge -> (path, line, context) of first sighting
        self.edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        self.reentrant: Set[str] = set()

    def add(self, outer: str, inner: str, where: Tuple[str, int, str]) -> None:
        self.edges.setdefault((outer, inner), where)


class _FnScan:
    """One function's direct acquisitions and call sites, each with the
    lexically-held set at that point."""

    def __init__(self) -> None:
        self.acquires: List[Tuple[str, FrozenSet[str], int]] = []
        self.calls: List[Tuple[Tuple[str, ...], FrozenSet[str], int]] = []


def resolve_lock_node(
    expr: ast.AST,
    info: Optional[_ClassInfo],
    mod_locks: _ModuleLocks,
    by_bare_name: Optional[Dict[str, List[_ClassInfo]]] = None,
) -> Optional[str]:
    """Canonical lock-node name for a ``with``-site expression, or None
    when the expression does not resolve to a discovered lock. Shared by
    the lockorder, blocking, and threads checkers so they all agree on
    what counts as "holding a named lock"."""
    if isinstance(expr, ast.Name) and expr.id in mod_locks.names:
        return f"{mod_locks.module.modname}.{expr.id}"
    if isinstance(expr, ast.Call):
        attr = _attr_base_chain(expr)
        if info is not None and attr in info.accessor_alias:
            return f"{info.qual}.{info.accessor_alias[attr]}"
        return None
    node = expr
    while isinstance(node, ast.Subscript):
        node = node.value
    if not isinstance(node, ast.Attribute):
        return None
    base = node.value
    while isinstance(base, ast.Subscript):
        base = base.value
    if isinstance(base, ast.Name):
        # self._lock / cls._lock / ClassName._lock (class-level lock)
        if base.id in ("self", "cls") or (
            info is not None and base.id == info.cls.name
        ):
            return info.node_for_attr(node.attr) if info is not None else None
        return None
    # self.<obj>.<lockattr>: one level of attribute-type inference —
    # NOT this class's lock (misattributing it would fabricate
    # self-edges and hide real cross-object orderings)
    if (
        info is not None
        and by_bare_name is not None
        and isinstance(base, ast.Attribute)
        and isinstance(base.value, ast.Name)
        and base.value.id == "self"
    ):
        tname = info.attr_types.get(base.attr)
        if tname is not None:
            cands = by_bare_name.get(tname, [])
            if len(cands) == 1:
                return cands[0].node_for_attr(node.attr)
    return None


def _scan_function(
    fn: ast.AST,
    info: Optional[_ClassInfo],
    mod_locks: _ModuleLocks,
    out: _FnScan,
    by_bare_name: Optional[Dict[str, List[_ClassInfo]]] = None,
) -> None:
    def lock_node(expr: ast.AST) -> Optional[str]:
        return resolve_lock_node(expr, info, mod_locks, by_bare_name)

    def visit(node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in node.items:
                scan_calls(item.context_expr, held)
                n = lock_node(item.context_expr)
                if n is not None:
                    out.acquires.append((n, frozenset(inner), item.context_expr.lineno))
                    inner.add(n)
            for stmt in node.body:
                visit(stmt, frozenset(inner))
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            body = node.body if isinstance(node.body, list) else [node.body]
            for stmt in body:
                visit(stmt, held)
            return
        if isinstance(node, ast.expr):
            scan_calls(node, held)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    def scan_calls(expr: ast.AST, held: FrozenSet[str]) -> None:
        for sub in ast.walk(expr):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            if isinstance(f, ast.Attribute):
                base = f.value
                if isinstance(base, ast.Name) and base.id == "self":
                    out.calls.append((("self", f.attr), held, sub.lineno))
                elif (
                    isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"
                ):
                    out.calls.append((("attr", base.attr, f.attr), held, sub.lineno))

    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        visit(stmt, frozenset())


def _load_allowlist(path: Optional[str]) -> Set[Tuple[str, str]]:
    import os

    out: Set[Tuple[str, str]] = set()
    if not path or not os.path.exists(path):
        return out
    with open(path, "r", encoding="utf-8") as f:
        for raw in f:
            line = raw.split("#", 1)[0].strip()
            if not line or "->" not in line:
                continue
            a, _, b = line.partition("->")
            out.add((a.strip(), b.strip()))
    return out


def check(
    modules: Sequence[Module],
    allowlist_path: Optional[str] = None,
    stale_out: Optional[List[Tuple[str, str]]] = None,
) -> List[Finding]:
    """``stale_out`` (when given) receives allowlist edges that no longer
    match any acquired-while-holding edge — dead waivers the CLI turns
    into errors (prunable with ``--prune-stale``)."""
    classes: Dict[str, _ClassInfo] = {}
    by_bare_name: Dict[str, List[_ClassInfo]] = {}
    mod_locks: Dict[str, _ModuleLocks] = {}
    for m in modules:
        mod_locks[m.modname] = _ModuleLocks(m)
        for cls in iter_classes(m):
            info = _collect_class_info(m, cls)
            classes[info.qual] = info
            by_bare_name.setdefault(cls.name, []).append(info)

    graph = _Graph()
    scans: Dict[Tuple[str, str], _FnScan] = {}  # (class qual, method) -> scan
    scan_meta: Dict[Tuple[str, str], Tuple[str, _ClassInfo]] = {}
    for m in modules:
        for cls in iter_classes(m):
            info = classes[f"{m.modname}.{cls.name}"]
            for node in info.lock_attrs:
                if info.reentrant(f"{info.qual}.{node}"):
                    graph.reentrant.add(f"{info.qual}.{node}")
            for method in iter_methods(cls):
                s = _FnScan()
                _scan_function(method, info, mod_locks[m.modname], s, by_bare_name)
                scans[(info.qual, method.name)] = s
                scan_meta[(info.qual, method.name)] = (m.relpath, info)

    # transitive lock sets, to fixpoint
    locks_of: Dict[Tuple[str, str], Set[str]] = {
        k: {n for n, _, _ in s.acquires} for k, s in scans.items()
    }

    def resolve(key: Tuple[str, str], ref: Tuple[str, ...]) -> Optional[Tuple[str, str]]:
        qual, _ = key
        info = classes[qual]
        if ref[0] == "self":
            callee = (qual, ref[1])
            return callee if callee in scans else None
        if ref[0] == "attr":
            tname = info.attr_types.get(ref[1])
            if tname is None:
                return None
            cands = by_bare_name.get(tname, [])
            if len(cands) == 1:
                callee = (cands[0].qual, ref[2])
                return callee if callee in scans else None
        return None

    changed = True
    iters = 0
    while changed and iters < 50:
        changed = False
        iters += 1
        for key, s in scans.items():
            cur = locks_of[key]
            for ref, _, _ in s.calls:
                callee = resolve(key, ref)
                if callee is not None:
                    extra = locks_of[callee] - cur
                    if extra:
                        cur |= extra
                        changed = True

    # edges: direct nesting + held-at-call x callee's transitive locks.
    # Re-acquiring a lock ALREADY in the held set cannot block, so it
    # orders nothing new against the other held locks — it only matters
    # as a self-deadlock on a non-reentrant Lock.
    for key, s in scans.items():
        relpath, info = scan_meta[key]
        ctx = f"{key[0].rsplit('.', 1)[-1]}.{key[1]}"
        for node, held, line in s.acquires:
            if node in held:
                if node not in graph.reentrant:
                    graph.add(node, node, (relpath, line, ctx))  # self-edge on Lock
                continue
            for h in held:
                graph.add(h, node, (relpath, line, ctx))
        for ref, held, line in s.calls:
            if not held:
                continue
            callee = resolve(key, ref)
            if callee is None:
                continue
            for inner in locks_of[callee]:
                if inner in held:
                    if inner not in graph.reentrant:
                        # callee re-acquires a plain Lock the caller holds
                        graph.add(
                            inner, inner, (relpath, line, ctx + " -> " + callee[1])
                        )
                    continue
                for h in held:
                    graph.add(h, inner, (relpath, line, ctx + " -> " + callee[1]))

    allow = _load_allowlist(allowlist_path)
    if stale_out is not None:
        stale_out.extend(sorted(a for a in allow if a not in graph.edges))
    edges = {e: w for e, w in graph.edges.items() if e not in allow}

    findings: List[Finding] = []
    # self-edges on non-reentrant locks
    for (a, b), (relpath, line, ctx) in sorted(edges.items()):
        if a == b:
            findings.append(
                Finding(
                    checker="lockorder",
                    path=relpath,
                    relpath=relpath,
                    line=line,
                    message=(
                        f"non-reentrant lock {a} re-acquired while held (in {ctx})"
                    ),
                )
            )

    # cycle detection (iterative Tarjan SCC over the directed edge set)
    adj: Dict[str, List[str]] = {}
    for (a, b) in edges:
        if a != b:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    onstack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v0: str) -> None:
        work = [(v0, iter(adj.get(v0, ())))]
        index[v0] = low[v0] = counter[0]
        counter[0] += 1
        stack.append(v0)
        onstack.add(v0)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    onstack.add(w)
                    work.append((w, iter(adj.get(w, ()))))
                    advanced = True
                    break
                elif w in onstack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)

    for comp in sccs:
        comp_set = set(comp)
        detail = "; ".join(
            f"{a}->{b} at {w[0]}:{w[1]} ({w[2]})"
            for (a, b), w in sorted(edges.items())
            if a in comp_set and b in comp_set and a != b
        )
        first = min(
            (w for (a, b), w in edges.items() if a in comp_set and b in comp_set),
            key=lambda w: (w[0], w[1]),
        )
        findings.append(
            Finding(
                checker="lockorder",
                path=first[0],
                relpath=first[0],
                line=first[1],
                message=(
                    "lock-order cycle (potential inversion): "
                    + " <-> ".join(comp)
                    + f" [{detail}]"
                ),
            )
        )
    return findings


def build_edges(modules: Sequence[Module]) -> Dict[Tuple[str, str], Tuple[str, int, str]]:
    """The raw acquired-while-holding edge set (debug/doc aid; the CLI's
    ``--dump-lock-graph`` prints it)."""
    classes: Dict[str, _ClassInfo] = {}
    by_bare_name: Dict[str, List[_ClassInfo]] = {}
    for m in modules:
        for cls in iter_classes(m):
            info = _collect_class_info(m, cls)
            classes[info.qual] = info
            by_bare_name.setdefault(cls.name, []).append(info)
    graph: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for m in modules:
        ml = _ModuleLocks(m)
        for cls in iter_classes(m):
            info = classes[f"{m.modname}.{cls.name}"]
            for method in iter_methods(cls):
                s = _FnScan()
                _scan_function(method, info, ml, s, by_bare_name)
                ctx = f"{cls.name}.{method.name}"
                for node, held, line in s.acquires:
                    for h in held:
                        if h != node:
                            graph.setdefault((h, node), (m.relpath, line, ctx))
    return graph
