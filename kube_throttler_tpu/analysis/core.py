"""Shared plumbing for the repo-native static analyzer.

The analyzer is AST-based and runs over the ``kube_throttler_tpu``
package only (tests drive it on fixture trees too). Every checker emits
:class:`Finding`s; the CLI (``__main__``) diffs them against a checked-in
baseline so vetted findings stay waived with a one-line justification
while anything new fails ``make lint`` and the tier-1 suite.

Baseline keys deliberately exclude line numbers: a finding is identified
by ``checker|relpath|message`` so unrelated edits shifting lines do not
churn the baseline, while any change to the violating construct itself
(attr name, lock name, call) produces a new key and fails.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class Finding:
    checker: str  # "guarded" | "lockorder" | "purity" | "registry"
    path: str  # path as given to the checker (absolute or repo-relative)
    line: int  # 1-based; 0 when the finding is not line-anchored
    message: str
    relpath: str = ""  # stable path used in the baseline key

    def key(self) -> str:
        return f"{self.checker}|{self.relpath or self.path}|{self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}] {self.message}"


@dataclass
class Module:
    """One parsed source file."""

    path: str
    relpath: str
    source: str
    tree: ast.Module
    lines: List[str] = field(default_factory=list)

    def __post_init__(self) -> None:
        if not self.lines:
            self.lines = self.source.splitlines()
        self._nodes: Optional[List[ast.AST]] = None

    @property
    def modname(self) -> str:
        # "engine/devicestate.py" -> "engine.devicestate"
        rel = self.relpath[:-3] if self.relpath.endswith(".py") else self.relpath
        return rel.replace(os.sep, ".").replace("/", ".")

    def walk(self) -> List[ast.AST]:
        """Every node of the tree, computed once. Eight checkers walk the
        same 90-odd files; materializing the node list once per file keeps
        full-repo ``make lint`` comfortably inside its latency budget."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree))
        return self._nodes


# (path, mtime_ns, size) -> Module — parses survive across run_checks
# calls in one process (the test tier drives the pipeline dozens of
# times; the CLI benefits when checkers re-load scoped subsets)
_PARSE_CACHE: Dict[Tuple[str, int, int], Module] = {}


def load_module(path: str, relpath: Optional[str] = None) -> Optional[Module]:
    try:
        st = os.stat(path)
        cache_key = (os.path.abspath(path), st.st_mtime_ns, st.st_size)
        cached = _PARSE_CACHE.get(cache_key)
        if cached is not None and cached.relpath == (relpath or path):
            return cached
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError):
        return None
    mod = Module(path=path, relpath=relpath or path, source=source, tree=tree)
    if len(_PARSE_CACHE) > 4096:  # a bound, not an eviction policy
        _PARSE_CACHE.clear()
    _PARSE_CACHE[cache_key] = mod
    return mod


def load_package(root: str, subdirs: Optional[Sequence[str]] = None) -> List[Module]:
    """Parse every ``.py`` under ``root`` (optionally restricted to the
    given first-level subdirs), relpaths relative to ``root``."""
    mods: List[Module] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        rel_dir = os.path.relpath(dirpath, root)
        if subdirs is not None:
            top = "" if rel_dir == "." else rel_dir.split(os.sep)[0]
            if rel_dir != "." and top not in subdirs:
                continue
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            m = load_module(path, rel)
            if m is not None:
                mods.append(m)
    return mods


def unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on parsed trees
        return "<expr>"


def literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def class_qualname(module: Module, cls: ast.ClassDef) -> str:
    return f"{module.modname}.{cls.name}"


def iter_classes(module: Module) -> Iterable[ast.ClassDef]:
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ClassDef):
            yield node


def iter_methods(cls: ast.ClassDef) -> Iterable[ast.FunctionDef]:
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


# ---------------------------------------------------------------- baseline


def load_baseline(path: str) -> Dict[str, str]:
    """``key  # justification`` lines -> {key: justification}. Blank lines
    and full-line comments are skipped."""
    out: Dict[str, str] = {}
    if not os.path.exists(path):
        return out
    with open(path, "r", encoding="utf-8") as f:
        for raw in f:
            line = raw.rstrip("\n")
            if not line.strip() or line.lstrip().startswith("#"):
                continue
            if "  #" in line:
                key, _, just = line.partition("  #")
                out[key.strip()] = just.strip()
            else:
                out[line.strip()] = ""
    return out


def apply_baseline(
    findings: Sequence[Finding], baseline: Dict[str, str]
) -> Tuple[List[Finding], List[Finding], List[str]]:
    """(new, waived, stale-keys). A baseline entry matches at most the
    findings sharing its key; stale keys are entries matching nothing —
    reported so fixed violations get their waivers deleted."""
    new: List[Finding] = []
    waived: List[Finding] = []
    seen_keys = set()
    for f in findings:
        k = f.key()
        seen_keys.add(k)
        (waived if k in baseline else new).append(f)
    stale = [k for k in baseline if k not in seen_keys]
    return new, waived, stale


# ------------------------------------------------------------- allow files


def load_pair_allowlist(path: Optional[str]) -> Dict[Tuple[str, str], str]:
    """``nodeA -> nodeB  # why`` lines -> {(a, b): justification}. The
    shared format of lockorder_allow.txt and blocking_allow.txt."""
    out: Dict[Tuple[str, str], str] = {}
    if not path or not os.path.exists(path):
        return out
    with open(path, "r", encoding="utf-8") as f:
        for raw in f:
            body, _, comment = raw.partition("#")
            body = body.strip()
            if not body or "->" not in body:
                continue
            a, _, b = body.partition("->")
            out[(a.strip(), b.strip())] = comment.strip()
    return out


def prune_file_lines(path: str, is_stale) -> int:
    """Rewrite ``path`` dropping every non-comment line for which
    ``is_stale(stripped_body)`` is true (comment/blank lines survive).
    Returns the number of lines removed — the ``--prune-stale`` autofix."""
    if not os.path.exists(path):
        return 0
    with open(path, "r", encoding="utf-8") as f:
        lines = f.readlines()
    kept: List[str] = []
    dropped = 0
    for raw in lines:
        body = raw.split("#", 1)[0].strip() if not raw.lstrip().startswith("#") else ""
        if body and is_stale(body):
            dropped += 1
            continue
        kept.append(raw)
    if dropped:
        with open(path, "w", encoding="utf-8") as f:
            f.writelines(kept)
    return dropped
