"""Checker 15 (gen-4): trust-boundary taint over the shard transports.

PR 16's security contract: the shard protocol is pickled Python —
``pickle.loads`` on attacker bytes is remote code execution, full stop
— so every frame crossing a non-loopback boundary is HMAC-authenticated
and ``read_frame`` verifies the tag with ``hmac.compare_digest``
BEFORE the payload ever reaches the deserializer. The contract only
holds if ``read_frame`` stays the ONLY ingestion point: one new
``pickle.loads(sock.recv(...))`` anywhere in the transport quietly
reopens the RCE class.

The checker makes the boundary structural over ``sharding/`` and
``engine/replication.py``:

- **sources** — network bytes: the result of ``X.recv(...)`` /
  ``X.recv_into(...)`` / ``X.accept()`` / ``X.makefile(...)`` (and
  reads off such a reader), plus parameters named ``rfile``/``sock``
  (the framing layer's reader-handle convention). Taint propagates
  through assignment, slicing, and concatenation, flow-insensitively
  to a local fixpoint;
- **sinks** — ``pickle.loads`` (exec-shaped: always) and ``json.loads``
  (flagged only when fed tainted bytes — the parser itself is safe,
  but an unauthenticated parse is still a boundary crossing worth a
  justified waiver);
- **the gate** — ``hmac.compare_digest``: a function that verifies a
  digest before deserializing (the ``read_frame`` shape, including its
  keyless trusted-local socketpair path — the gate is present, keying
  is the caller's deployment contract) satisfies the rule.

Two finding shapes:

1. a sink fed tainted bytes in a function with no ``compare_digest``
   gate — unauthenticated deserialization of network bytes;
2. any ``pickle.loads`` in the transport scope outside a gated
   function — a frame-ingestion point bypassing the authenticated
   framing layer, even when this checker cannot see the bytes' origin
   (pickle of locally-produced bytes belongs outside the transport or
   in ``baseline.txt`` with a justification).

One structural exemption, shape 2 only: ``sharding/shmring.py``. The
shared-memory event ring never carries network bytes — the segment is
created by the supervisor, mode 0600 on the local host, attached only
by the worker it spawned, and the TCP transport cannot reach it — so
its rare ``ROW_BLOB`` ``pickle.loads`` deserializes bytes this process
tree wrote into its own memory. That is the same trust statement as the
keyless socketpair pickle stream (whose gate is present but unkeyed).
Shape 1 still applies there in full: the moment network-sourced bytes
flow into the module, the exemption does NOT cover them.

Waivers go in ``baseline.txt`` (checker-agnostic keys) with mandatory
justifications; stale entries FAIL the run as usual.
"""

from __future__ import annotations

import ast
from typing import List, Sequence, Set

from .core import Finding, Module, iter_classes, iter_methods

_SCOPE_PREFIXES = ("sharding/",)
_SCOPE_FILES = ("engine/replication.py",)

_SOURCE_ATTRS = {"recv", "recv_into", "accept", "makefile"}
_TAINTED_PARAMS = {"rfile", "sock"}

# Shape-2 ("bypass") exemption: modules whose pickle.loads calls
# deserialize same-host bytes this process tree wrote itself (see the
# module docstring's trust-domain note). Shape 1 still applies.
_SHM_EXEMPT_FILES = ("sharding/shmring.py",)


def in_scope(module: Module) -> bool:
    rel = module.relpath.replace("\\", "/")
    return rel.startswith(_SCOPE_PREFIXES) or rel in _SCOPE_FILES


def _names_in(expr: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


def _has_source_call(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _SOURCE_ATTRS
        ):
            return True
    return False


def _is_gated(fn: ast.AST) -> bool:
    """True when the function calls ``hmac.compare_digest`` (the
    read_frame auth gate) anywhere in its body."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "compare_digest":
                return True
            if isinstance(f, ast.Name) and f.id == "compare_digest":
                return True
    return False


def _tainted_names(fn: ast.AST) -> Set[str]:
    """Flow-insensitive local taint set: params named like network
    readers, plus anything assigned from a source call or an
    already-tainted name, to fixpoint."""
    tainted: Set[str] = set()
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        for a in list(fn.args.args) + list(fn.args.kwonlyargs):
            if a.arg in _TAINTED_PARAMS:
                tainted.add(a.arg)
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                value, targets = node.value, [node.target]
            elif isinstance(node, ast.AugAssign):
                value, targets = node.value, [node.target]
            else:
                continue
            if _has_source_call(value) or (_names_in(value) & tainted):
                for t in targets:
                    if isinstance(t, ast.Name) and t.id not in tainted:
                        tainted.add(t.id)
                        changed = True
                    elif isinstance(t, ast.Tuple):
                        for el in t.elts:
                            if isinstance(el, ast.Name) and el.id not in tainted:
                                tainted.add(el.id)
                                changed = True
    return tainted


def _sink_kind(call: ast.Call) -> str:
    """'pickle' / 'json' / '' for a deserializer call."""
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "loads":
        if isinstance(f.value, ast.Name):
            if f.value.id == "pickle":
                return "pickle"
            if f.value.id == "json":
                return "json"
        return "pickle"  # aliased pickle-ish loads: treat as exec-shaped
    if isinstance(f, ast.Name) and f.id == "loads":
        return "pickle"
    return ""


def check(modules: Sequence[Module]) -> List[Finding]:
    findings: List[Finding] = []
    emitted: Set[tuple] = set()

    def scan(m: Module, fn: ast.AST, ctx: str) -> None:
        gated = _is_gated(fn)
        tainted = _tainted_names(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            kind = _sink_kind(node)
            if not kind:
                continue
            arg = node.args[0] if node.args else None
            fed_taint = arg is not None and (
                _has_source_call(arg) or bool(_names_in(arg) & tainted)
            )
            if fed_taint and not gated:
                key = (m.relpath, ctx, kind, "taint")
                if key not in emitted:
                    emitted.add(key)
                    findings.append(
                        Finding(
                            checker="taint",
                            path=m.relpath,
                            relpath=m.relpath,
                            line=node.lineno,
                            message=(
                                f"unauthenticated {kind}.loads of network bytes "
                                f"(no hmac.compare_digest gate in {ctx})"
                            ),
                        )
                    )
            elif kind == "pickle" and not gated:
                if m.relpath.replace("\\", "/").endswith(_SHM_EXEMPT_FILES):
                    continue  # same-host shm blobs — docstring exemption
                key = (m.relpath, ctx, "pickle", "bypass")
                if key not in emitted:
                    emitted.add(key)
                    findings.append(
                        Finding(
                            checker="taint",
                            path=m.relpath,
                            relpath=m.relpath,
                            line=node.lineno,
                            message=(
                                f"frame-ingestion point bypasses the "
                                f"authenticated framing layer: pickle.loads "
                                f"outside the read_frame gate (in {ctx})"
                            ),
                        )
                    )

    for m in modules:
        if not in_scope(m):
            continue
        claimed = set()
        for cls in iter_classes(m):
            for method in iter_methods(cls):
                claimed.add(id(method))
                scan(m, method, f"{cls.name}.{method.name}")
        for node in m.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if id(node) in claimed:
                    continue
                scan(m, node, node.name)

    findings.sort(key=lambda f: (f.relpath, f.line, f.message))
    return findings
