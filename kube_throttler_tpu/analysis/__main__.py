"""CLI for the repo-native static analyzer.

Exit status: 0 when every finding is either absent or waived in the
baseline AND every waiver is still live; 1 when new findings exist (they
are printed ``path:line: [checker] message``) OR any baseline/allowlist
entry is stale (a waiver whose finding no longer exists is waiver rot —
it hides nothing today and will silently hide a regression tomorrow).
``--prune-stale`` rewrites the baseline and allow files dropping the
dead entries instead of failing on them.
"""

from __future__ import annotations

import argparse
import sys

from . import (
    CHECKERS,
    DEFAULT_ALLOWLIST,
    DEFAULT_BASELINE,
    DEFAULT_BLOCKING_ALLOWLIST,
    DEFAULT_DEADLINE_ALLOWLIST,
    DEFAULT_EPOCH_ALLOWLIST,
    PACKAGE_ROOT,
    run_checks,
)
from .core import apply_baseline, load_baseline, load_package, prune_file_lines
from .lockgraph import build_edges


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kube_throttler_tpu.analysis",
        description=(
            "lock discipline / JAX purity / registry / blocking / thread / "
            "exception-safety / protocol / dtype / donation / retrace / "
            "envguard / epochs / deadlines / taint static analyzer"
        ),
    )
    ap.add_argument("--root", default=PACKAGE_ROOT, help="package root to analyze")
    ap.add_argument(
        "--checks",
        default=",".join(CHECKERS),
        help=f"comma-separated subset of: {', '.join(CHECKERS)}",
    )
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--allowlist", default=None)
    ap.add_argument("--blocking-allowlist", default=None)
    ap.add_argument("--epoch-allowlist", default=None)
    ap.add_argument("--deadline-allowlist", default=None)
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding (ignore the baseline)",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="append new findings to the baseline with TODO justifications",
    )
    ap.add_argument(
        "--prune-stale",
        action="store_true",
        help="delete stale baseline/allowlist entries instead of failing on them",
    )
    ap.add_argument(
        "--dump-lock-graph",
        action="store_true",
        help="print the raw acquired-while-holding edges and exit",
    )
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    checks = tuple(c.strip() for c in args.checks.split(",") if c.strip())
    bad = [c for c in checks if c not in CHECKERS]
    if bad:
        ap.error(f"unknown checker(s): {', '.join(bad)}")

    modules = load_package(args.root)
    if args.dump_lock_graph:
        for (a, b), (path, line, ctx) in sorted(build_edges(modules).items()):
            print(f"{a} -> {b}    # {path}:{line} ({ctx})")
        return 0

    # stale-allowlist enforcement only makes sense when the allow file
    # and the analyzed tree belong together: the default allow files
    # against the default root (the repo gate), or an explicitly given
    # file (fixture tests). A custom --root against the repo's defaults
    # is mismatched by construction — findings are still filtered, but
    # unmatched entries are not waiver rot.
    import os as _os

    root_is_default = _os.path.abspath(args.root) == _os.path.abspath(PACKAGE_ROOT)
    enforce_stale = {
        "lockorder": root_is_default or args.allowlist is not None,
        "blocking": root_is_default or args.blocking_allowlist is not None,
        "epochs": root_is_default or args.epoch_allowlist is not None,
        "deadlines": root_is_default or args.deadline_allowlist is not None,
    }
    allowlist = args.allowlist if args.allowlist is not None else DEFAULT_ALLOWLIST
    blocking_allowlist = (
        args.blocking_allowlist
        if args.blocking_allowlist is not None
        else DEFAULT_BLOCKING_ALLOWLIST
    )
    epoch_allowlist = (
        args.epoch_allowlist
        if args.epoch_allowlist is not None
        else DEFAULT_EPOCH_ALLOWLIST
    )
    deadline_allowlist = (
        args.deadline_allowlist
        if args.deadline_allowlist is not None
        else DEFAULT_DEADLINE_ALLOWLIST
    )

    stale_allow: dict = {}
    findings = run_checks(
        modules,
        checks,
        allowlist_path=allowlist,
        blocking_allowlist_path=blocking_allowlist,
        epoch_allowlist_path=epoch_allowlist,
        deadline_allowlist_path=deadline_allowlist,
        stale_allow_out=stale_allow,
    )
    stale_allow = {
        checker: pairs
        for checker, pairs in stale_allow.items()
        if enforce_stale.get(checker)
    }
    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    new, waived, stale = apply_baseline(findings, baseline)

    for f in new:
        print(f.render())
    if args.write_baseline and new:
        with open(args.baseline, "a", encoding="utf-8") as fh:
            for f in new:
                fh.write(f"{f.key()}  # TODO: justify or fix\n")
        print(f"wrote {len(new)} new waiver(s) to {args.baseline}", file=sys.stderr)
        return 0

    allow_paths = {
        "lockorder": allowlist,
        "blocking": blocking_allowlist,
        "epochs": epoch_allowlist,
        "deadlines": deadline_allowlist,
    }
    n_stale_allow = sum(len(v) for v in stale_allow.values())
    if args.prune_stale:
        pruned = 0
        if stale:
            stale_set = set(stale)
            pruned += prune_file_lines(
                args.baseline, lambda body: body in stale_set
            )
        for checker, pairs in stale_allow.items():
            if not pairs:
                continue
            dead = {f"{a} -> {b}" for a, b in pairs}

            def _is_stale(body: str, dead=dead) -> bool:
                a, _, b = body.partition("->")
                return f"{a.strip()} -> {b.strip()}" in dead

            pruned += prune_file_lines(allow_paths[checker], _is_stale)
        if pruned and not args.quiet:
            print(f"pruned {pruned} stale waiver(s)", file=sys.stderr)
        stale, stale_allow, n_stale_allow = [], {}, 0

    for k in stale:
        print(f"error: stale baseline entry (fix: --prune-stale): {k}")
    for checker, pairs in stale_allow.items():
        for a, b in pairs:
            print(
                f"error: stale {checker} allowlist entry (fix: --prune-stale): "
                f"{a} -> {b}"
            )
    if not args.quiet:
        print(
            f"analysis: {len(new)} new finding(s), {len(waived)} waived, "
            f"{len(stale) + n_stale_allow} stale waiver(s) over "
            f"{len(modules)} file(s)",
            file=sys.stderr,
        )
    return 1 if (new or stale or n_stale_allow) else 0


if __name__ == "__main__":
    sys.exit(main())
