"""CLI for the repo-native static analyzer.

Exit status: 0 when every finding is either absent or waived in the
baseline; 1 when new findings exist (they are printed ``path:line:
[checker] message``). Stale baseline entries (waivers whose finding no
longer exists) are reported as warnings so they get deleted, but do not
fail the run.
"""

from __future__ import annotations

import argparse
import sys

from . import (
    CHECKERS,
    DEFAULT_ALLOWLIST,
    DEFAULT_BASELINE,
    PACKAGE_ROOT,
    run_checks,
)
from .core import apply_baseline, load_baseline, load_package
from .lockgraph import build_edges


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kube_throttler_tpu.analysis",
        description="lock discipline / JAX purity / registry static analyzer",
    )
    ap.add_argument("--root", default=PACKAGE_ROOT, help="package root to analyze")
    ap.add_argument(
        "--checks",
        default=",".join(CHECKERS),
        help=f"comma-separated subset of: {', '.join(CHECKERS)}",
    )
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--allowlist", default=DEFAULT_ALLOWLIST)
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding (ignore the baseline)",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="append new findings to the baseline with TODO justifications",
    )
    ap.add_argument(
        "--dump-lock-graph",
        action="store_true",
        help="print the raw acquired-while-holding edges and exit",
    )
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    checks = tuple(c.strip() for c in args.checks.split(",") if c.strip())
    bad = [c for c in checks if c not in CHECKERS]
    if bad:
        ap.error(f"unknown checker(s): {', '.join(bad)}")

    modules = load_package(args.root)
    if args.dump_lock_graph:
        for (a, b), (path, line, ctx) in sorted(build_edges(modules).items()):
            print(f"{a} -> {b}    # {path}:{line} ({ctx})")
        return 0

    findings = run_checks(modules, checks, allowlist_path=args.allowlist)
    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    new, waived, stale = apply_baseline(findings, baseline)

    for f in new:
        print(f.render())
    if args.write_baseline and new:
        with open(args.baseline, "a", encoding="utf-8") as fh:
            for f in new:
                fh.write(f"{f.key()}  # TODO: justify or fix\n")
        print(f"wrote {len(new)} new waiver(s) to {args.baseline}", file=sys.stderr)
        return 0
    if not args.quiet:
        for k in stale:
            print(f"warning: stale baseline entry (delete it): {k}", file=sys.stderr)
        print(
            f"analysis: {len(new)} new finding(s), {len(waived)} waived, "
            f"{len(stale)} stale waiver(s) over {len(modules)} file(s)",
            file=sys.stderr,
        )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
