"""Checker 3: JAX trace purity.

Entry points are the traced bodies in ``ops/``, ``parallel/``, and
``sharding/``:

- functions decorated ``@jax.jit`` / ``@partial(jax.jit, ...)``;
- local functions handed to ``shard_map(...)`` (first positional arg);
- kernels handed to ``pl.pallas_call(...)``.

From each entry the checker computes the statically-resolvable call
graph (module-level defs, ``from ..x import y`` aliases inside the
analyzed set) and flags, anywhere reachable:

- host-effect calls: ``time.*``, ``random.*`` / ``np.random.*``,
  ``threading.*``, Prometheus metric mutation (``observe``,
  ``observe_key``, ``inc``, ``set_key`` — ``.set`` is exempt because
  ``x.at[i].set(v)`` is the JAX functional update), and fault-plan hits
  (``*.check(site)`` / ``*.maybe_raise(site)`` on a ``faults`` object) —
  any of these inside a traced body either silently bakes a tracer-time
  value into the compiled program or mutates host state once per COMPILE
  instead of once per call;
- Python ``if``/``while`` branching on a known-traced parameter of the
  entry (parameters minus ``static_argnames``): structure checks
  (``x is None``, ``x.shape``/``ndim``/``dtype``, ``len(x)``,
  ``isinstance``) are exempt — those are trace-time Python values.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, Module, literal_str, unparse

_HOST_MODULES = {"time", "random", "threading"}
_METRIC_MUTATORS = {"observe", "observe_key", "inc", "set_key"}
_FAULT_METHODS = {"check", "maybe_raise"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}


def _decorator_jit_static(dec: ast.AST) -> Optional[Tuple[bool, Set[str]]]:
    """(is_jit, static_argnames) if the decorator applies jax.jit."""
    text = unparse(dec)
    if "jit" not in text:
        return None
    static: Set[str] = set()
    if isinstance(dec, ast.Call):
        for kw in dec.keywords:
            if kw.arg == "static_argnames":
                try:
                    val = ast.literal_eval(kw.value)
                except ValueError:
                    val = ()
                if isinstance(val, str):
                    static = {val}
                else:
                    static = {str(v) for v in val}
    # match jax.jit / jit / partial(jax.jit, ...)
    if text in ("jax.jit", "jit") or text.startswith(("jax.jit(", "jit(", "partial(jax.jit", "functools.partial(jax.jit", "partial(jit")):
        return True, static
    return None


class _FnIndex:
    """Module-level function defs + import aliases for call resolution."""

    def __init__(self, modules: Sequence[Module]):
        self.defs: Dict[Tuple[str, str], Tuple[Module, ast.FunctionDef]] = {}
        self.aliases: Dict[str, Dict[str, Tuple[str, str]]] = {}
        by_name: Dict[str, List[Tuple[str, str]]] = {}
        for m in modules:
            self.aliases.setdefault(m.modname, {})
            for node in ast.walk(m.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    key = (m.modname, node.name)
                    self.defs[key] = (m, node)
                    by_name.setdefault(node.name, []).append(key)
        for m in modules:
            amap = self.aliases[m.modname]
            for node in ast.walk(m.tree):
                if isinstance(node, ast.ImportFrom):
                    for alias in node.names:
                        local = alias.asname or alias.name
                        # resolve by bare function name across the analyzed
                        # set (package-relative imports; unique names only)
                        cands = by_name.get(alias.name, [])
                        if len(cands) == 1:
                            amap[local] = cands[0]
                        elif len(cands) > 1:
                            # prefer a module whose name matches the import tail
                            tail = (node.module or "").split(".")[-1]
                            matched = [c for c in cands if c[0].split(".")[-1] == tail]
                            if len(matched) == 1:
                                amap[local] = matched[0]

    def resolve(self, modname: str, name: str) -> Optional[Tuple[str, str]]:
        if (modname, name) in self.defs:
            return (modname, name)
        return self.aliases.get(modname, {}).get(name)


def _call_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        # strip a `.__wrapped__` unjitted-body access: full_update_step
        # .__wrapped__(...) calls the same def
        if f.attr == "__wrapped__":
            return f.value.id
        return None
    return None


def _entry_points(
    modules: Sequence[Module],
) -> List[Tuple[Module, ast.FunctionDef, Set[str], str]]:
    """(module, fn, static_argnames, why) for every traced entry."""
    out = []
    seen: Set[int] = set()
    for m in modules:
        local_defs: Dict[str, ast.FunctionDef] = {}
        for node in ast.walk(m.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local_defs.setdefault(node.name, node)
        for node in ast.walk(m.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    res = _decorator_jit_static(dec)
                    if res:
                        if id(node) not in seen:
                            seen.add(id(node))
                            out.append((m, node, res[1], "@jax.jit"))
                        break
            elif isinstance(node, ast.Call):
                name = None
                f = node.func
                if isinstance(f, ast.Name):
                    name = f.id
                elif isinstance(f, ast.Attribute):
                    name = f.attr
                if name in ("shard_map", "pallas_call") and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Name) and arg.id in local_defs:
                        fn = local_defs[arg.id]
                        if id(fn) not in seen:
                            seen.add(id(fn))
                            out.append((m, fn, set(), name))
    return out


def _banned_calls(module: Module, fn: ast.FunctionDef, where: str) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute):
            base = f.value
            # time.monotonic(), random.random(), threading.Lock() ...
            if isinstance(base, ast.Name) and base.id in _HOST_MODULES:
                findings.append(
                    Finding(
                        checker="purity",
                        path=module.path,
                        relpath=module.relpath,
                        line=node.lineno,
                        message=(
                            f"host call {base.id}.{f.attr}() inside traced "
                            f"body {where}"
                        ),
                    )
                )
                continue
            # np.random.* / numpy.random.*
            if (
                isinstance(base, ast.Attribute)
                and base.attr == "random"
                and isinstance(base.value, ast.Name)
                and base.value.id in ("np", "numpy")
            ):
                findings.append(
                    Finding(
                        checker="purity",
                        path=module.path,
                        relpath=module.relpath,
                        line=node.lineno,
                        message=(
                            f"host call np.random.{f.attr}() inside traced "
                            f"body {where}"
                        ),
                    )
                )
                continue
            if f.attr in _METRIC_MUTATORS:
                findings.append(
                    Finding(
                        checker="purity",
                        path=module.path,
                        relpath=module.relpath,
                        line=node.lineno,
                        message=(
                            f"metric mutation .{f.attr}() inside traced "
                            f"body {where}"
                        ),
                    )
                )
                continue
            if f.attr in _FAULT_METHODS and "faults" in unparse(base):
                findings.append(
                    Finding(
                        checker="purity",
                        path=module.path,
                        relpath=module.relpath,
                        line=node.lineno,
                        message=(
                            f"fault-plan hit .{f.attr}() inside traced "
                            f"body {where}"
                        ),
                    )
                )
    return findings


def _traced_branch_findings(
    module: Module, fn: ast.FunctionDef, static: Set[str], where: str
) -> List[Finding]:
    params = {
        a.arg
        for a in list(fn.args.args)
        + list(fn.args.posonlyargs)
        + list(fn.args.kwonlyargs)
        if a.arg not in ("self", "cls")
    }
    traced = params - static
    if not traced:
        return []

    findings: List[Finding] = []

    def names_in_test(test: ast.AST) -> Set[str]:
        """Traced param names used as VALUES in the test (structure-only
        uses — .shape/.ndim/.dtype, len(), is None, isinstance — are
        stripped before collection)."""
        hits: Set[str] = set()

        def walk(node: ast.AST) -> None:
            if isinstance(node, ast.Attribute):
                if node.attr in _STATIC_ATTRS:
                    return  # x.shape / x.req.shape etc: static at trace time
                walk(node.value)
                return
            if isinstance(node, ast.Subscript):
                walk(node.value)
                walk(node.slice)
                return
            if isinstance(node, ast.Call):
                fname = (
                    node.func.id
                    if isinstance(node.func, ast.Name)
                    else getattr(node.func, "attr", "")
                )
                if fname in ("len", "isinstance", "getattr", "hasattr", "type"):
                    return
                for a in node.args:
                    walk(a)
                for kw in node.keywords:
                    walk(kw.value)
                return
            if isinstance(node, ast.Compare):
                # `x is None` / `x is not None`: python-structure check
                if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                    return
                walk(node.left)
                for c in node.comparators:
                    walk(c)
                return
            if isinstance(node, ast.Name):
                if node.id in traced:
                    hits.add(node.id)
                return
            for child in ast.iter_child_nodes(node):
                walk(child)

        walk(test)
        return hits

    for node in ast.walk(fn):
        if isinstance(node, (ast.If, ast.While)):
            hits = names_in_test(node.test)
            if hits:
                kw = "if" if isinstance(node, ast.If) else "while"
                findings.append(
                    Finding(
                        checker="purity",
                        path=module.path,
                        relpath=module.relpath,
                        line=node.lineno,
                        message=(
                            f"Python {kw} on traced parameter(s) "
                            f"{', '.join(sorted(hits))} in {where} — use "
                            "jnp.where/lax.cond, or mark the arg static"
                        ),
                    )
                )
    return findings


def check(modules: Sequence[Module]) -> List[Finding]:
    # sharding/ carries no jit entries today, but its workers own full
    # device planes — a kernel landing there must be scanned, not missed
    # by a stale scope list (the PR 10 purity-gap audit)
    scoped = [
        m
        for m in modules
        if m.relpath.replace("\\", "/").startswith(("ops/", "parallel/", "sharding/"))
    ] or list(modules)
    index = _FnIndex(scoped)
    entries = _entry_points(scoped)

    findings: List[Finding] = []
    visited: Set[Tuple[str, str]] = set()

    def reach(module: Module, fn: ast.FunctionDef, why: str) -> None:
        key = (module.modname, fn.name)
        if key in visited:
            return
        visited.add(key)
        where = f"{module.modname}.{fn.name} (via {why})"
        findings.extend(_banned_calls(module, fn, where))
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = _call_name(node)
                if name is None:
                    continue
                resolved = index.resolve(module.modname, name)
                if resolved is not None:
                    callee_mod, callee_fn = index.defs[resolved]
                    reach(callee_mod, callee_fn, why)

    for module, fn, static, why in entries:
        where = f"{module.modname}.{fn.name} ({why})"
        findings.extend(_traced_branch_findings(module, fn, static, where))
        reach(module, fn, why)
    # dedup: one function reachable from several entries reports once per
    # site (visited-set keeps bodies single-visit; entries may still share
    # a first visit — identical keys collapse at baseline level anyway)
    uniq = {}
    for f in findings:
        uniq.setdefault((f.key(), f.line), f)
    return list(uniq.values())
