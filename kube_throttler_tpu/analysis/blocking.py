"""Checker 5: blocking calls under a named lock.

Every flip-latency regression the scenario corpus has caught so far had
the same anatomy: something slow — a file sync, a socket send, a device
dispatch, a sleep — ran while a hot-path lock was held, and every thread
behind that lock inherited the wait (the PR 8 relist-storm 409 retry
storm head-of-line-blocked the committer shards exactly this way). This
checker makes the class structural: it reuses the lockorder checker's
lock discovery and lexical hold tracking, computes which functions
(transitively) perform a blocking operation, and flags every site where
a blocking operation is reached while a named lock is held.

Blocking operations (the matcher, :func:`_blocking_desc`):

- ``time.sleep`` / any ``.sleep()`` (fault-plan delays included);
- ``os.fsync`` and file opens (``open``/``os.open``);
- socket I/O (``sendall``/``sendto``/``recv``/``recv_into``/``connect``/
  ``accept``/``getresponse``) and ``.makefile()``;
- framed-pickle IPC (``send_frame``/``read_frame`` — sharding/ipc.py);
- blocking RPC/future waits: ``.request()``, ``.result()``, thread
  ``.join()`` (zero-positional-arg form only — ``",".join(xs)`` is not a
  thread join);
- subprocess waits (``subprocess.run``/``check_call``/``check_output``/
  ``Popen``, ``.communicate()``, ``.wait()`` on a ``proc``-named base);
- device dispatch: calls to ``@jax.jit`` entry functions (discovered the
  same way the purity checker finds them), ``pallas_call``, and
  ``.block_until_ready()``.

Propagation is interprocedural to fixpoint over the same call shapes the
lockorder checker resolves (``self.m()``, ``self.attr.m()`` with one
level of attribute-type inference, and unique bare-name module
functions), plus one *observer bridge*: classes that register methods via
``add_event_handler(..., self.m)`` / ``add_batch_listener(self)`` have
those methods charged as callees of any ``_dispatch_locked`` /
``_dispatch_batch_locked`` method — the store's handler fan-out runs
under the store lock, and the journal's group commit (file write + flush
+ optional fsync) lives at the end of that edge. That is precisely the
chunked ``STATUS_WRITE_CHUNK`` hold: intended, measured, and therefore
*waived with a justification* rather than invisible.

Intended holds go in ``blocking_allow.txt``, one per line::

    engine.journal.StoreJournal._lock -> os.fsync()  # group-commit durability IS the journal lock's job

A waiver keys on ``(lock node, blocking descriptor)``, so one line
covers every path that reaches that pair. Allow entries matching no
finding are reported stale (the CLI errors on them; ``--prune-stale``
deletes them).
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .core import Finding, Module, iter_classes, iter_methods, load_pair_allowlist, unparse
from .lockgraph import (
    _ClassInfo,
    _ModuleLocks,
    _collect_class_info,
    resolve_lock_node,
)

_SOCKET_ATTRS = {
    "sendall", "sendto", "recv", "recv_into", "connect", "accept",
    "getresponse", "makefile",
}
_RPC_ATTRS = {"request", "result", "communicate", "block_until_ready"}
_IPC_FNS = {"send_frame", "read_frame"}
_SUBPROCESS_FNS = {"run", "check_call", "check_output", "call"}


def _attr_parts(func: ast.AST) -> Tuple[Optional[str], Optional[str]]:
    """(base text, attr) for an Attribute callee, else (None, name)."""
    if isinstance(func, ast.Attribute):
        return unparse(func.value), func.attr
    if isinstance(func, ast.Name):
        return None, func.id
    return None, None


def _jit_entry_names(modules: Sequence[Module]) -> Set[str]:
    """Names of traced entry points — ``@jax.jit`` defs and functions
    handed to ``pallas_call``/``shard_map`` — anywhere in the analyzed
    set. Calling one dispatches device work (compile on first call)."""
    names: Set[str] = set()
    for m in modules:
        for node in m.walk():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if "jit" in unparse(dec):
                        names.add(node.name)
                        break
            elif isinstance(node, ast.Call):
                _, fname = _attr_parts(node.func)
                if fname in ("pallas_call", "shard_map") and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Name):
                        names.add(arg.id)
    return names


def _blocking_desc(call: ast.Call, jit_entries: Set[str]) -> Optional[str]:
    """Stable descriptor string when ``call`` is a blocking operation,
    else None. Descriptors are the allowlist's right-hand side — keep
    them short and argument-free."""
    base, attr = _attr_parts(call.func)
    if attr is None:
        return None
    if base is None:
        # bare-name calls
        if attr == "open":
            return "open()"
        if attr in _IPC_FNS:
            return f"{attr}()"
        if attr == "Popen":
            return "subprocess.Popen()"
        if attr == "pallas_call":
            return "pallas_call()"
        if attr == "sleep":
            return "sleep()"
        if attr in jit_entries:
            return f"jit:{attr}()"
        return None
    if attr == "sleep":
        return "sleep()"
    if base == "os" and attr in ("fsync", "fdatasync"):
        return f"os.{attr}()"
    if base == "os" and attr == "open":
        return "open()"
    if base == "subprocess" and (attr in _SUBPROCESS_FNS or attr == "Popen"):
        return f"subprocess.{attr}()"
    if attr in _SOCKET_ATTRS:
        return f".{attr}()"
    if attr in _RPC_ATTRS:
        return f".{attr}()"
    if attr in _IPC_FNS:
        return f"{attr}()"
    if attr == "wait" and "proc" in base:
        return "proc.wait()"
    if attr == "join" and not call.args:
        # zero positional args = thread join; ",".join(xs) always has one
        return ".join()"
    if attr in jit_entries:
        return f"jit:{attr}()"
    return None


class _Scan:
    """One function's blocking calls and call refs, with held sets."""

    def __init__(self) -> None:
        # (descriptor, held set, line)
        self.blocking: List[Tuple[str, FrozenSet[str], int]] = []
        # (ref, held set, line): ref is ("self", m) | ("attr", a, m) | ("name", f)
        self.calls: List[Tuple[Tuple[str, ...], FrozenSet[str], int]] = []


def _scan_function(
    fn: ast.AST,
    info: Optional[_ClassInfo],
    mod_locks: _ModuleLocks,
    by_bare_name: Dict[str, List[_ClassInfo]],
    jit_entries: Set[str],
    out: _Scan,
) -> None:
    def visit(node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in node.items:
                scan_expr(item.context_expr, held)
                n = resolve_lock_node(item.context_expr, info, mod_locks, by_bare_name)
                if n is not None:
                    inner.add(n)
            for stmt in node.body:
                visit(stmt, frozenset(inner))
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            body = node.body if isinstance(node.body, list) else [node.body]
            for stmt in body:
                visit(stmt, held)
            return
        if isinstance(node, ast.expr):
            scan_expr(node, held)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    def scan_expr(expr: ast.AST, held: FrozenSet[str]) -> None:
        for sub in ast.walk(expr):
            if not isinstance(sub, ast.Call):
                continue
            desc = _blocking_desc(sub, jit_entries)
            if desc is not None:
                out.blocking.append((desc, held, sub.lineno))
                continue
            f = sub.func
            if isinstance(f, ast.Attribute):
                base = f.value
                if isinstance(base, ast.Name) and base.id == "self":
                    out.calls.append((("self", f.attr), held, sub.lineno))
                elif (
                    isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"
                ):
                    out.calls.append((("attr", base.attr, f.attr), held, sub.lineno))
            elif isinstance(f, ast.Name):
                out.calls.append((("name", f.id), held, sub.lineno))

    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        visit(stmt, frozenset())


def _observer_bridges(
    modules: Sequence[Module], classes: Dict[str, _ClassInfo]
) -> Tuple[Set[Tuple[str, str]], List[Tuple[str, str]]]:
    """(dispatchers, handlers): dispatcher methods are every
    ``_dispatch_locked``/``_dispatch_batch_locked``; handlers are every
    method registered via ``*.add_event_handler(..., self.m)`` or a class
    passing itself to ``*.add_batch_listener(self)`` (its ``on_batch``).
    The checker charges every handler as a callee of every dispatcher —
    coarse on purpose: handler fan-out is one dynamic seam, and a
    blocking handler blocks whichever dispatch lock is held."""
    dispatchers: Set[Tuple[str, str]] = set()
    handlers: List[Tuple[str, str]] = []

    def scan_registrations(fn: ast.AST, self_qual: Optional[str]) -> None:
        # local-name -> class qual for `x = ClassName(...)` in this scope
        # (journal/attach-style registrations pass a local, not self)
        local_types: Dict[str, str] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                fname = node.value.func
                cname = (
                    fname.id if isinstance(fname, ast.Name)
                    else fname.attr if isinstance(fname, ast.Attribute) else None
                )
                if cname and cname[:1].isupper():
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            cands = [
                                q for q, info in classes.items()
                                if info.cls.name == cname
                            ]
                            if len(cands) == 1:
                                local_types[t.id] = cands[0]
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            _, fname = _attr_parts(node.func)
            if fname == "add_event_handler":
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    if isinstance(arg, ast.Attribute) and isinstance(
                        arg.value, ast.Name
                    ):
                        if arg.value.id == "self" and self_qual is not None:
                            handlers.append((self_qual, arg.attr))
                        elif arg.value.id in local_types:
                            handlers.append((local_types[arg.value.id], arg.attr))
            elif fname == "add_batch_listener":
                for a in node.args:
                    if isinstance(a, ast.Name):
                        if a.id == "self" and self_qual is not None:
                            handlers.append((self_qual, "on_batch"))
                        elif a.id in local_types:
                            handlers.append((local_types[a.id], "on_batch"))

    for m in modules:
        claimed = set()
        for cls in iter_classes(m):
            qual = f"{m.modname}.{cls.name}"
            for method in iter_methods(cls):
                claimed.add(id(method))
                if method.name in ("_dispatch_locked", "_dispatch_batch_locked"):
                    dispatchers.add((qual, method.name))
                scan_registrations(method, qual)
        for node in m.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if id(node) not in claimed:
                    scan_registrations(node, None)
    return dispatchers, handlers


def check(
    modules: Sequence[Module],
    allowlist_path: Optional[str] = None,
    stale_out: Optional[List[Tuple[str, str]]] = None,
) -> List[Finding]:
    classes: Dict[str, _ClassInfo] = {}
    by_bare_name: Dict[str, List[_ClassInfo]] = {}
    mod_locks: Dict[str, _ModuleLocks] = {}
    for m in modules:
        mod_locks[m.modname] = _ModuleLocks(m)
        for cls in iter_classes(m):
            info = _collect_class_info(m, cls)
            classes[info.qual] = info
            by_bare_name.setdefault(cls.name, []).append(info)
    jit_entries = _jit_entry_names(modules)

    scans: Dict[Tuple[str, str], _Scan] = {}
    scan_meta: Dict[Tuple[str, str], Tuple[str, Optional[_ClassInfo]]] = {}
    module_fns: Dict[str, List[Tuple[str, str]]] = {}  # bare name -> keys
    for m in modules:
        method_ids = set()
        for cls in iter_classes(m):
            info = classes[f"{m.modname}.{cls.name}"]
            for method in iter_methods(cls):
                method_ids.add(id(method))
                s = _Scan()
                _scan_function(method, info, mod_locks[m.modname], by_bare_name,
                               jit_entries, s)
                scans[(info.qual, method.name)] = s
                scan_meta[(info.qual, method.name)] = (m.relpath, info)
        for node in m.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if id(node) in method_ids:
                    continue
                s = _Scan()
                _scan_function(node, None, mod_locks[m.modname], by_bare_name,
                               jit_entries, s)
                key = (m.modname, node.name)
                scans[key] = s
                scan_meta[key] = (m.relpath, None)
                module_fns.setdefault(node.name, []).append(key)

    dispatchers, handler_methods = _observer_bridges(modules, classes)

    # transitive blocking descriptors, to fixpoint
    blocks_of: Dict[Tuple[str, str], Set[str]] = {
        k: {d for d, _, _ in s.blocking} for k, s in scans.items()
    }

    def resolve(key: Tuple[str, str], ref: Tuple[str, ...]) -> Optional[Tuple[str, str]]:
        owner, _ = key
        if ref[0] == "self":
            callee = (owner, ref[1])
            return callee if callee in scans else None
        if ref[0] == "attr":
            info = classes.get(owner)
            if info is None:
                return None
            tname = info.attr_types.get(ref[1])
            if tname is None:
                return None
            cands = by_bare_name.get(tname, [])
            if len(cands) == 1:
                callee = (cands[0].qual, ref[2])
                return callee if callee in scans else None
            return None
        if ref[0] == "name":
            cands = module_fns.get(ref[1], [])
            if len(cands) == 1:
                return cands[0]
        return None

    changed = True
    iters = 0
    while changed and iters < 50:
        changed = False
        iters += 1
        for key, s in scans.items():
            cur = blocks_of[key]
            for ref, _, _ in s.calls:
                callee = resolve(key, ref)
                if callee is not None:
                    extra = blocks_of[callee] - cur
                    if extra:
                        cur |= extra
                        changed = True
            if key in dispatchers:
                for h in handler_methods:
                    if h in blocks_of:
                        extra = blocks_of[h] - cur
                        if extra:
                            cur |= extra
                            changed = True

    # findings: one per (held lock, descriptor) occurrence
    allow = load_pair_allowlist(allowlist_path)
    seen_pairs: Set[Tuple[str, str]] = set()
    findings: List[Finding] = []
    emitted: Set[Tuple[str, str, str]] = set()  # (relpath, lock, desc) dedup

    def emit(relpath: str, line: int, lock: str, desc: str, ctx: str) -> None:
        seen_pairs.add((lock, desc))
        if (lock, desc) in allow:
            return
        if (relpath, lock, desc) in emitted:
            return
        emitted.add((relpath, lock, desc))
        findings.append(
            Finding(
                checker="blocking",
                path=relpath,
                relpath=relpath,
                line=line,
                message=f"blocking {desc} while holding {lock} (in {ctx})",
            )
        )

    for key, s in scans.items():
        relpath, info = scan_meta[key]
        ctx = f"{key[0].rsplit('.', 1)[-1]}.{key[1]}" if info is not None else key[1]
        for desc, held, line in s.blocking:
            for lock in held:
                emit(relpath, line, lock, desc, ctx)
        for ref, held, line in s.calls:
            if not held:
                continue
            callee = resolve(key, ref)
            extra: Set[str] = set()
            if callee is not None:
                extra |= blocks_of[callee]
            if key in dispatchers:
                pass  # dispatcher methods hold no locks themselves in-tree
            for desc in sorted(extra):
                for lock in held:
                    emit(relpath, line, lock, desc, f"{ctx} -> {ref[-1]}")
        if key in dispatchers:
            # the bridge: handlers run at dispatch sites; dispatch sites
            # are charged at their CALLERS' held sets via blocks_of, so
            # nothing extra to do here beyond the fixpoint above
            pass

    if stale_out is not None:
        stale_out.extend(sorted(p for p in allow if p not in seen_pairs))
    findings.sort(key=lambda f: (f.relpath, f.line, f.message))
    return findings
