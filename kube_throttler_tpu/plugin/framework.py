"""Minimal scheduling-framework types.

The reference plugs into k8s.io/kubernetes' scheduler framework; the new
framework is standalone, so the tiny surface the plugin actually touches is
defined here: Status codes (framework.NewStatus usage at plugin.go:155,179,
214,234), cluster events for requeue hints (plugin.go:263-279), and the
event-recorder interface (plugin.go:190-201).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum

from ..utils.lockorder import guard_attrs, make_lock
from typing import Dict, List, Optional, Tuple


class StatusCode(Enum):
    SUCCESS = "Success"
    ERROR = "Error"
    UNSCHEDULABLE = "Unschedulable"
    UNSCHEDULABLE_AND_UNRESOLVABLE = "UnschedulableAndUnresolvable"


@dataclass(frozen=True)
class Status:
    code: StatusCode = StatusCode.SUCCESS
    reasons: Tuple[str, ...] = ()

    def is_success(self) -> bool:
        return self.code == StatusCode.SUCCESS

    def is_unschedulable(self) -> bool:
        """Capacity-shaped rejection (either unschedulable code) — the
        rejections gang-aware preemption may resolve; ERROR is not one."""
        return self.code in (
            StatusCode.UNSCHEDULABLE,
            StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE,
        )

    def message(self) -> str:
        return ", ".join(self.reasons)


@dataclass(frozen=True)
class ClusterEvent:
    resource: str
    action_type: str = "All"


@dataclass(frozen=True)
class PodEvent:
    pod_key: str
    event_type: str  # "Warning" | "Normal"
    reason: str
    action: str
    note: str


class EventRecorder:
    def eventf(
        self, pod_key: str, event_type: str, reason: str, action: str, note: str
    ) -> None:  # pragma: no cover — interface
        raise NotImplementedError


@guard_attrs
class RecordingEventRecorder(EventRecorder):
    """Stores emitted events (the integration tier asserts on them the way
    the reference asserts on FailedScheduling / ResourceRequestsExceeds…
    events — util_pod_test.go:68-92).

    Identical events aggregate into one entry with a count (like the real
    kube event recorder's correlator) and distinct entries are capped at
    ``max_events`` with oldest-first eviction — a daemon retrying one stuck
    pod every flush interval must not grow memory without bound."""

    GUARDED_BY = {
        "events": "self._lock",
        "counts": "self._lock",
    }

    def __init__(self, max_events: int = 10_000) -> None:
        self._lock = make_lock("plugin.event_recorder")
        self._max_events = max_events
        self.events: List[PodEvent] = []
        self.counts: Dict[PodEvent, int] = {}

    def eventf(self, pod_key: str, event_type: str, reason: str, action: str, note: str) -> None:
        ev = PodEvent(pod_key, event_type, reason, action, note)
        with self._lock:
            if ev in self.counts:
                self.counts[ev] += 1
                return
            self.counts[ev] = 1
            self.events.append(ev)
            if len(self.events) > self._max_events:
                evicted = self.events.pop(0)
                self.counts.pop(evicted, None)

    def events_for(self, pod_key: str) -> List[PodEvent]:
        with self._lock:
            return [e for e in self.events if e.pod_key == pod_key]
