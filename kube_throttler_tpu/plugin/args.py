"""Plugin argument decoding (reference plugin_args.go:29-60).

Same field names (including the ``kubeconfig`` JSON key whose Go field is the
``KubeConifg`` typo — SURVEY §2.3 quirk 5), same defaults and validation:
``name`` and ``targetSchedulerName`` required; interval defaults to 15s;
threadiness defaults to CPU count.

``reconcileTemporaryThresholdInterval`` is decoded-but-unused in the
reference (plugin_args.go:53-55 → plugin.go:93,104 → dropped; override
wakeups are event-driven via NextOverrideHappensIn). Here it IS honored: the
plugin passes it to both controllers as ``resync_interval``, the periodic
enqueue-all backstop (controllers/base.py ``_resync``) that replaces the
reference's 5-minute informer resync. Note the cadence tradeoff: the 15s
default re-enqueues every responsible key 20× more often than the
reference's 5-minute resync — cheap here because the workqueue dedups and
the batched reconcile pays one device aggregate per drain, but deployments
with very large throttle counts that don't need fast staleness repair can
raise it (e.g. ``"5m"``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from datetime import timedelta
from typing import Any, Mapping, Optional

DEFAULT_RECONCILE_TEMPORARY_THRESHOLD_INTERVAL = timedelta(seconds=15)


@dataclass(frozen=True)
class KubeThrottlerPluginArgs:
    name: str
    target_scheduler_name: str
    kubeconfig: str = ""
    reconcile_temporary_threshold_interval: timedelta = (
        DEFAULT_RECONCILE_TEMPORARY_THRESHOLD_INTERVAL
    )
    controller_threadiness: int = 0
    num_key_mutex: int = 0
    # optional expiry for scheduler-cycle reservations (None = the
    # reference's reserve-until-observed lifetime): a scheduler that dies
    # between Reserve and Bind must not pin capacity forever, and crash
    # recovery rebases the remaining budget on restore
    # (engine/reservations.py)
    reservation_ttl: Optional[timedelta] = None
    # expiry for GANG group reserves (engine/gang.py): a half-bound gang
    # whose scheduler died must free ALL ranks' capacity together. None
    # falls back to reservation_ttl (and to reserve-until-observed when
    # that is None too)
    gang_reservation_ttl: Optional[timedelta] = None
    # policy-as-data (policy/spec.py, docs/policy.md): the ``policies``
    # config key — a list of PolicySpec dicts with RFC3339 activation
    # windows (first active wins, the temporaryThresholdOverrides
    # discipline). Empty ⇒ the built-in default: weights 1.0, preemption
    # off. Hot-swappable at runtime via plugin.set_policy_specs.
    policy_specs: tuple = ()


def decode_plugin_args(config: Mapping[str, Any]) -> KubeThrottlerPluginArgs:
    name = str(config.get("name", "") or "")
    if not name:
        raise ValueError("Name must not be empty")
    target = str(config.get("targetSchedulerName", "") or "")
    if not target:
        raise ValueError("TargetSchedulerName must not be empty")

    raw_interval = config.get("reconcileTemporaryThresholdInterval", 0)
    if isinstance(raw_interval, str) and raw_interval:
        # Go duration strings: "15s", "1m30s", "500ms" (strict grammar)
        interval = _parse_go_duration(raw_interval)
    elif isinstance(raw_interval, (int, float)) and raw_interval:
        interval = timedelta(seconds=float(raw_interval))
    else:
        interval = timedelta(0)
    if interval < timedelta(0):
        # a negative interval would turn the resync backstop into a hot loop
        # (workqueue.add_after fires immediately for secs <= 0)
        raise ValueError(
            "reconcileTemporaryThresholdInterval must not be negative: "
            f"{raw_interval!r}"
        )
    if interval == timedelta(0):
        interval = DEFAULT_RECONCILE_TEMPORARY_THRESHOLD_INTERVAL

    threadiness = int(config.get("controllerThrediness", 0) or 0)
    if threadiness == 0:
        threadiness = os.cpu_count() or 1

    raw_ttl = config.get("reservationTTL", 0)
    if isinstance(raw_ttl, str) and raw_ttl:
        reservation_ttl = _parse_go_duration(raw_ttl)
    elif isinstance(raw_ttl, (int, float)) and raw_ttl:
        reservation_ttl = timedelta(seconds=float(raw_ttl))
    else:
        reservation_ttl = None
    if reservation_ttl is not None and reservation_ttl <= timedelta(0):
        # zero/negative would expire every reservation at birth — the
        # admission inequality's `reserved` term silently vanishes
        raise ValueError(f"reservationTTL must be positive: {raw_ttl!r}")

    raw_gang_ttl = config.get("gangReservationTTL", 0)
    if isinstance(raw_gang_ttl, str) and raw_gang_ttl:
        gang_ttl = _parse_go_duration(raw_gang_ttl)
    elif isinstance(raw_gang_ttl, (int, float)) and raw_gang_ttl:
        gang_ttl = timedelta(seconds=float(raw_gang_ttl))
    else:
        gang_ttl = None
    if gang_ttl is not None and gang_ttl <= timedelta(0):
        raise ValueError(f"gangReservationTTL must be positive: {raw_gang_ttl!r}")

    from ..policy.spec import policy_specs_from_config

    return KubeThrottlerPluginArgs(
        name=name,
        target_scheduler_name=target,
        kubeconfig=str(config.get("kubeconfig", "") or ""),
        reconcile_temporary_threshold_interval=interval,
        controller_threadiness=threadiness,
        num_key_mutex=int(config.get("numKeyMutex", 0) or 0) or 128,
        reservation_ttl=reservation_ttl,
        gang_reservation_ttl=gang_ttl,
        policy_specs=policy_specs_from_config(config.get("policies")),
    )


_GO_DURATION_UNITS = {
    "ns": 1e-9,
    "us": 1e-6,
    "µs": 1e-6,  # U+00B5 micro sign
    "μs": 1e-6,  # U+03BC greek mu
    "ms": 1e-3,
    "s": 1.0,
    "m": 60.0,
    "h": 3600.0,
}

_GO_DURATION_TOKEN = None  # compiled lazily below


def _parse_go_duration(s: str) -> timedelta:
    """Strict Go ``time.ParseDuration`` grammar (reference validates args via
    it, plugin_args.go:177-195): optional sign, then one or more
    ``<decimal><unit>`` tokens consuming the WHOLE string. Trailing garbage
    ("15sgarbage"), missing units ("15"), and empty input all raise — config
    typos must fail loudly, not silently truncate.
    """
    import re

    global _GO_DURATION_TOKEN
    if _GO_DURATION_TOKEN is None:
        units = "|".join(sorted(_GO_DURATION_UNITS, key=len, reverse=True))
        _GO_DURATION_TOKEN = re.compile(
            r"(\d+(?:\.\d*)?|\.\d+)(" + units + r")"
        )

    orig, sign = s, 1.0
    if s[:1] in ("+", "-"):
        sign = -1.0 if s[0] == "-" else 1.0
        s = s[1:]
    if s == "0":  # Go's special case: bare zero needs no unit
        return timedelta(0)
    if not s:
        raise ValueError(f"invalid duration: {orig!r}")
    total, pos = 0.0, 0
    while pos < len(s):
        m = _GO_DURATION_TOKEN.match(s, pos)
        if m is None:
            raise ValueError(f"invalid duration: {orig!r}")
        total += float(m.group(1)) * _GO_DURATION_UNITS[m.group(2)]
        pos = m.end()
    return timedelta(seconds=sign * total)
