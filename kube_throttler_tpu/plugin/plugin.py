"""KubeThrottler plugin: the admission front-end (reference plugin.go).

PreFilter gates pods on both controllers' check results with the reference's
exact result statuses, reason-string formats, and Warning-event emission
(plugin.go:148-215); Reserve/Unreserve book-keep scheduler-cycle
reservations (217-257); EventsToRegister mirrors the requeue hints (263-279).
"""

from __future__ import annotations

import logging
import os
from typing import List, Optional, Sequence

from ..api.pod import Pod
from ..api.types import cluster_throttle_names, throttle_names
from ..client import Clientset, InformerBundle, Listers, SharedInformerFactory
from ..controllers import ClusterThrottleController, ThrottleController
from ..engine.devicestate import DeviceStateManager
from ..engine.store import Store
from ..health import Health
from ..metrics import (
    ClusterThrottleMetricsRecorder,
    Registry,
    StatusLagMetrics,
    ThrottleMetricsRecorder,
    register_breaker_metrics,
    register_watch_metrics,
)
from ..utils.tracing import PhaseTracer, vlog
from ..utils.clock import Clock, RealClock
from .args import KubeThrottlerPluginArgs
from .framework import ClusterEvent, EventRecorder, Status, StatusCode

logger = logging.getLogger(__name__)

PLUGIN_NAME = "kube-throttler"

from ..api.serialization import API_GROUP as SCHEME_GROUP  # noqa: E402
from ..api.serialization import VERSION as SCHEME_VERSION  # noqa: E402


class KubeThrottler:
    """Implements PreFilter / Reserve / Unreserve / EventsToRegister."""

    def __init__(
        self,
        args: KubeThrottlerPluginArgs,
        store: Store,
        clock: Optional[Clock] = None,
        event_recorder: Optional[EventRecorder] = None,
        use_device: bool = True,
        start_workers: bool = False,
        metrics_registry=None,
        status_writer=None,
    ):
        clock = clock or RealClock()
        self.args = args
        self.store = store
        self.event_recorder = event_recorder
        self.metrics_registry = metrics_registry or Registry()
        self.tracer = PhaseTracer(self.metrics_registry)
        # ORDER MATTERS: the device mirror registers its store handlers
        # FIRST so its rows/masks update before the informer fan-out reaches
        # the controllers' enqueues — a worker draining the key immediately
        # then reconciles against device state >= the event.
        self.device_manager = (
            DeviceStateManager(store, args.name, args.target_scheduler_name)
            if use_device
            else None
        )
        # Generated-machinery analog, wired for real (plugin.go:71-130):
        # a typed clientset over the cache, the schedule-group informer
        # factory plus the separate core factory (whose pod informer carries
        # the namespace indexer, plugin.go:81-84), and indexer-backed listers
        # that every controller read goes through. Informer-level resync is
        # disabled: the controllers' resync_interval
        # (reconcileTemporaryThresholdInterval) is the periodic backstop.
        self.clientset = Clientset(store)
        self.informer_factory = SharedInformerFactory(store, resync_period=0.0)
        self.core_informer_factory = SharedInformerFactory(store, resync_period=0.0)
        self.informers = InformerBundle(self.informer_factory, self.core_informer_factory)
        self.listers = Listers.from_factories(
            self.informer_factory, self.core_informer_factory
        )
        self.informer_factory.start()
        self.core_informer_factory.start()
        if not (
            self.informer_factory.wait_for_cache_sync()
            and self.core_informer_factory.wait_for_cache_sync()
        ):  # pragma: no cover — the store mirror syncs synchronously
            raise RuntimeError("informer caches failed to sync")
        self.throttle_ctr = ThrottleController(
            throttler_name=args.name,
            target_scheduler_name=args.target_scheduler_name,
            store=store,
            clock=clock,
            threadiness=args.controller_threadiness,
            num_key_mutex=args.num_key_mutex,
            device_manager=self.device_manager,
            metrics_recorder=ThrottleMetricsRecorder(self.metrics_registry),
            resync_interval=args.reconcile_temporary_threshold_interval,
            listers=self.listers,
            informers=self.informers,
            status_writer=status_writer,
            reservation_ttl=args.reservation_ttl,
        )
        self.cluster_throttle_ctr = ClusterThrottleController(
            throttler_name=args.name,
            target_scheduler_name=args.target_scheduler_name,
            store=store,
            clock=clock,
            threadiness=args.controller_threadiness,
            num_key_mutex=args.num_key_mutex,
            device_manager=self.device_manager,
            metrics_recorder=ClusterThrottleMetricsRecorder(self.metrics_registry),
            resync_interval=args.reconcile_temporary_threshold_interval,
            listers=self.listers,
            informers=self.informers,
            status_writer=status_writer,
            reservation_ttl=args.reservation_ttl,
        )
        if self.device_manager is not None:
            self.device_manager.tracer = self.tracer
            self.device_manager.fallback_counter = self.metrics_registry.counter_vec(
                "kube_throttler_device_fallback_total",
                "dispatch failures that opened the device circuit breaker "
                "(decisions/reconciles served host-side meanwhile)",
                ["surface"],
            )
            register_breaker_metrics(self.metrics_registry, self.device_manager)
            # reservation replay onto freshly allocated device columns
            # (throttle re-creation / throttlerName handover) reads these
            self.device_manager.reservation_sources = {
                "throttle": self.throttle_ctr.cache,
                "clusterthrottle": self.cluster_throttle_ctr.cache,
            }
            # micro-batched ingest: each batch's single flip-candidate pass
            # promotes stale-flag keys straight into the priority lanes
            # (one add_all_priority per kind per batch — devicestate
            # _promote_ingest_flips)
            # promotion order is policy-weighted (flip_priorities reads
            # the controller's flip_priority_fn, wired below once the
            # policy engine exists): valued accel classes' flips drain
            # ahead of their hi-lane peers
            self.device_manager.install_flip_promoters(
                {
                    "throttle": (
                        lambda keys, _c=self.throttle_ctr: _c.workqueue.add_all_priority(
                            keys, priorities=_c.flip_priorities(keys)
                        )
                    ),
                    "clusterthrottle": (
                        lambda keys, _c=self.cluster_throttle_ctr: _c.workqueue.add_all_priority(
                            keys, priorities=_c.flip_priorities(keys)
                        )
                    ),
                }
            )
        self.throttle_ctr.tracer = self.tracer
        self.cluster_throttle_ctr.tracer = self.tracer
        # gang (pod-group) admission ledger (engine/gang.py): all-or-
        # nothing reserve/rollback over BOTH kinds' reservation caches.
        # The device mirror learns of member reservations through the same
        # on_reservation_change hook the per-pod paths use; the journal is
        # late-bound by the CLI (standalone mode) for GANG audit stamps.
        from ..engine.gang import GangLedger

        dm = self.device_manager
        self.gang = GangLedger(
            caches={
                "throttle": self.throttle_ctr.cache,
                "clusterthrottle": self.cluster_throttle_ctr.cache,
            },
            clock=clock,
            on_change=(
                (
                    lambda kind, key: dm.on_reservation_change(
                        kind,
                        key,
                        self.throttle_ctr.cache
                        if kind == "throttle"
                        else self.cluster_throttle_ctr.cache,
                    )
                )
                if dm is not None
                else None
            ),
            default_ttl=(args.gang_reservation_ttl or args.reservation_ttl),
        )
        self.throttle_ctr.gang_ledger = self.gang
        self.cluster_throttle_ctr.gang_ledger = self.gang
        # member lifecycle: bound members admit, deleted pre-admission
        # members roll the whole group back (store → gang lock order)
        store.add_event_handler("Pod", self.gang.on_pod_event, replay=False)
        # policy engine + preemption coordinator (policy/, docs/policy.md):
        # policy-as-data value weights drive victim selection and the flip
        # promotion priorities below; the coordinator owns the journaled,
        # gang-atomic eviction cycle the scheduler triggers when a high-
        # priority group is capacity-rejected. The journal is late-bound
        # by the CLI like the gang ledger's.
        from ..policy.preempt import PreemptionCoordinator
        from ..policy.spec import PolicyEngine

        self.policy = PolicyEngine(specs=args.policy_specs, clock=clock)
        self.preempt = PreemptionCoordinator(
            policy=self.policy,
            kind_controllers=(
                ("throttle", self.throttle_ctr),
                ("clusterthrottle", self.cluster_throttle_ctr),
            ),
            store=store,
            gang_ledger=self.gang,
            device_manager=self.device_manager,
        )
        # admission ages + evicted-then-readmitted churn (both gated on
        # the active policy enabling preemption — zero per-pod state kept
        # otherwise, the PR 11 memory posture)
        store.add_event_handler("Pod", self.preempt.on_pod_event, replay=False)
        # the controllers' flip promotion order consumes the policy
        # weights: a throttle declaring accel classes the policy values
        # above default promotes ahead of its hi-lane peers (workqueue
        # (-priority, seq) ordering)
        self.throttle_ctr.flip_priority_fn = self._policy_flip_priority(
            self.throttle_ctr
        )
        self.cluster_throttle_ctr.flip_priority_fn = self._policy_flip_priority(
            self.cluster_throttle_ctr
        )
        from ..metrics import register_gang_metrics, register_preempt_metrics

        self._gang_check_hist = register_gang_metrics(self.metrics_registry, self.gang)
        self.preempt.select_hist = register_preempt_metrics(
            self.metrics_registry, self.preempt
        )
        # local-path flip/total status-lag histograms; a lane-aware remote
        # writer (AsyncStatusCommitter) observes the "remote" path itself
        lag_metrics = StatusLagMetrics(self.metrics_registry, "local")
        self.throttle_ctr.lag_metrics = lag_metrics
        self.cluster_throttle_ctr.lag_metrics = lag_metrics
        register_watch_metrics(self.metrics_registry)
        # /readyz component registry (health.py): the daemon surface serves
        # its snapshot; the CLI adds journal/reflector components when they
        # exist (standalone vs remote mode)
        self.health = Health()
        if self.device_manager is not None:
            self.health.register("device", self._device_health)
        self.health.register("workqueues", self._workqueue_health)
        self._coalescer = None
        # interned-verdict cache (engine/verdictcache.py): pre_filter /
        # pre_filter_batch probe it before any plane walk. Requires the
        # device manager — the fingerprint reads its epoch planes.
        # KT_VERDICT_CACHE=0 disables; KT_VERDICT_CACHE_SIZE bounds it.
        self.verdict_cache = None
        if (
            self.device_manager is not None
            and os.environ.get("KT_VERDICT_CACHE", "1") != "0"
        ):
            from ..engine.verdictcache import VerdictCache

            try:
                capacity = int(os.environ.get("KT_VERDICT_CACHE_SIZE", "65536"))
            except ValueError:
                capacity = 65536  # malformed override must not kill serving
            self.verdict_cache = VerdictCache(capacity=capacity)
        # verdict-coherence assassin (utils/epochassert.py): when armed,
        # sampled cache hits are shadow-recomputed through the uncached
        # oracle route — a divergence at an unchanged epoch sum proves a
        # missed bump and raises StaleVerdict at first observation
        from ..utils import epochassert as _epochassert

        self._epoch_assert = _epochassert.enabled()
        if start_workers:
            self.throttle_ctr.start()
            self.cluster_throttle_ctr.start()

    @property
    def name(self) -> str:
        return PLUGIN_NAME

    # ------------------------------------------------------------- health

    def _device_health(self):
        # an open/half-open breaker is DEGRADED, not down: the host oracle
        # serves every admission surface, at worse latency
        state = self.device_manager.breaker_state()
        return ("ok" if state == "closed" else "degraded"), {"breaker": state}

    # a workqueue this deep means reconciles are falling behind events by
    # minutes — still serving (degraded), but an operator should look
    WORKQUEUE_DEGRADED_DEPTH = 10_000

    def _workqueue_health(self):
        depths = {
            "throttle": len(self.throttle_ctr.workqueue),
            "clusterthrottle": len(self.cluster_throttle_ctr.workqueue),
        }
        state = (
            "degraded"
            if max(depths.values()) >= self.WORKQUEUE_DEGRADED_DEPTH
            else "ok"
        )
        return state, depths

    def coalescer(self, window_s: float = 0.0, max_batch: int = 64):
        """The micro-batching pre_filter front-end for CONCURRENT callers:
        one fused device dispatch per window instead of one per caller
        (plugin/coalesce.py). First call constructs it; parameters are
        fixed thereafter."""
        if self._coalescer is None:
            from .coalesce import PreFilterCoalescer

            self._coalescer = PreFilterCoalescer(self, window_s, max_batch)
        return self._coalescer

    # -------------------------------------------------------------- prefilter

    def pre_filter(self, pod: Pod) -> Status:
        with self.tracer.trace("prefilter"):
            return self._pre_filter(pod)

    def _pre_filter(self, pod: Pod) -> Status:
        cache = self.verdict_cache
        if cache is None:
            return self._pre_filter_uncached(pod)
        fp = self.device_manager.verdict_fingerprint(pod)
        if fp is None:  # no arena / unknown namespace — uncacheable
            return self._pre_filter_uncached(pod)
        key, esum = fp
        hit = cache.get(key, esum)
        if hit is not None:
            if self._epoch_assert:
                from ..utils import epochassert

                if epochassert.should_check():
                    epochassert.check_hit(self, pod, key, esum, hit)
            return hit
        status = self._pre_filter_uncached(pod)
        if self._cacheable(status):
            # validate-after-compute: re-read the fingerprint and insert
            # only if no covered mutation landed while we computed — a
            # racing flip/reservation then suppresses the insert instead
            # of poisoning the cache (see engine/verdictcache.py)
            if self.device_manager.verdict_fingerprint(pod) == fp:
                cache.put(key, esum, status)
        return status

    def _pre_filter_uncached(self, pod: Pod, emit_events: bool = True) -> Status:
        try:
            thr4 = self.throttle_ctr.check_throttled(pod, False)
        except Exception as e:
            return Status(StatusCode.ERROR, (str(e),))

        try:
            clthr4 = self.cluster_throttle_ctr.check_throttled(pod, False)
        except Exception as e:
            return Status(StatusCode.ERROR, (str(e),))

        return self._compose_prefilter_status(pod, thr4, clthr4, emit_events)

    @staticmethod
    def _cacheable(status: Status) -> bool:
        """ERROR statuses carry transient causes; exceeds statuses emit a
        Warning event per PreFilter call (plugin.go:191-201) — a cache hit
        would swallow the emission. Neither may be interned."""
        return status.code is not StatusCode.ERROR and not any(
            "[pod-requests-exceeds-threshold]" in r for r in status.reasons
        )

    def _compose_prefilter_status(
        self, pod: Pod, thr4, clthr4, emit_events: bool = True
    ) -> Status:
        """Reason composition from both kinds' check_throttled 4-tuples —
        ordering mirrors plugin.go:182-214 exactly. Shared by the direct
        path and the micro-batching coalescer (which produces the tuples
        from one fused dispatch)."""
        thr_active, thr_insufficient, thr_exceeds, thr_affected = thr4
        clthr_active, clthr_insufficient, clthr_exceeds, clthr_affected = clthr4

        if (
            len(thr_active) + len(thr_insufficient) + len(thr_exceeds)
            + len(clthr_active) + len(clthr_insufficient) + len(clthr_exceeds)
            == 0
        ):
            vlog(5, "pod %s is not throttled by any throttle/clusterthrottle", pod.key)
            return Status(StatusCode.SUCCESS)

        # reason ordering mirrors plugin.go:182-214 exactly
        reasons: List[str] = []
        if clthr_exceeds:
            reasons.append(
                f"clusterthrottle[pod-requests-exceeds-threshold]={','.join(cluster_throttle_names(clthr_exceeds))}"
            )
        if thr_exceeds:
            reasons.append(
                f"throttle[pod-requests-exceeds-threshold]={','.join(throttle_names(thr_exceeds))}"
            )
        if (clthr_exceeds or thr_exceeds) and emit_events and self.event_recorder is not None:
            names = cluster_throttle_names(clthr_exceeds) + throttle_names(thr_exceeds)
            self.event_recorder.eventf(
                pod.key,
                "Warning",
                "ResourceRequestsExceedsThrottleThreshold",
                self.name,
                "It won't be scheduled unless decreasing resource requests or "
                "increasing ClusterThrottle/Throttle threshold because its "
                f"resource requests exceeds their thresholds: {','.join(names)}",
            )
        if clthr_active:
            reasons.append(f"clusterthrottle[active]={','.join(cluster_throttle_names(clthr_active))}")
        if thr_active:
            reasons.append(f"throttle[active]={','.join(throttle_names(thr_active))}")
        if clthr_insufficient:
            reasons.append(
                f"clusterthrottle[insufficient]={','.join(cluster_throttle_names(clthr_insufficient))}"
            )
        if thr_insufficient:
            reasons.append(f"throttle[insufficient]={','.join(throttle_names(thr_insufficient))}")
        # plugin.go:157-style V(2) visibility into every rejection
        vlog(2, "pod %s is unschedulable: %s", pod.key, "; ".join(reasons))
        return Status(StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE, tuple(reasons))

    def pre_filter_batch(self) -> dict:
        """Bulk admission triage: ONE device pass classifies every stored pod
        against both kinds' full throttle state (no per-pod loop — the
        100k×10k check matrix the reference evaluates pod-by-pod in Go runs
        as two batched kernels here). Without a device manager, falls back to
        the per-pod host oracle.

        Returns ``{"schedulable": {pod_key: bool}, "errors": [pod_key, ...]}``;
        schedulable mirrors PreFilter's gate (no active/insufficient/exceeds
        throttle of either kind, plugin.go:177-180). Pods whose Namespace
        object is missing land in ``errors`` — the per-pod path returns an
        ERROR status for them (clusterthrottle_controller.go:273-276), so the
        batch must not report them schedulable. Per-pod reasons stay on
        ``pre_filter``.
        """
        with self.tracer.trace("prefilter_batch"):
            known_ns = {ns.name for ns in self.listers.namespaces.list()}
            schedulable: dict = {}
            errors: list = []
            dm = self.device_manager
            if dm is not None and self.verdict_cache is not None:
                # intra-batch dedupe: the degenerate mix collapses to a few
                # hundred (shape, accel, cols) groups — one representative
                # eval per group replaces the O(P) classification AND warms
                # the verdict cache for the single-pod serving path in one
                # pass. Returns None when the mix is NOT degenerate enough
                # (or too large to fingerprint) — the fused device kernel
                # is the better batch engine there.
                with self.tracer.trace("batch_dedupe"):
                    deduped = self._pre_filter_batch_dedupe(known_ns)
                if deduped is not None:
                    return deduped
            if dm is not None:
                # one coherent device snapshot for BOTH kinds (a single
                # lock hold inside check_batch_all) — the composed verdict
                # matches one point in the event stream. On breaker-open/
                # failure, batch calls serve from the host oracle below.
                # Sub-phases traced for the bench's dispatch/merge
                # breakdown. JAX dispatch is async, so batch_dispatch
                # explicitly blocks on the verdict arrays — otherwise the
                # kernel time would surface inside batch_merge's first
                # np.asarray and the split would point at the wrong phase.
                with self.tracer.trace("batch_dispatch"):
                    batches = dm.guarded("batch", dm.check_batch_all, False)
                    if batches is not None:
                        import jax

                        jax.block_until_ready(
                            [ok for (_, ok, _) in batches.values()]
                        )
                if batches is not None:
                    with self.tracer.trace("batch_merge"):
                        per_kind = {
                            kind: (ok, rows) for kind, (_, ok, rows) in batches.items()
                        }
                        schedulable, errors = self._merge_verdicts(per_kind, known_ns)
                        self._apply_accel_class_overrides(schedulable, errors)
                    return {"schedulable": schedulable, "errors": errors}

            # host oracle, side-effect-free (no Warning events — triage
            # only, matching the device path)
            for pod in self.listers.pods.list():
                try:
                    ta, ti, te, _ = self.throttle_ctr.check_throttled(pod, False)
                    ca, ci, ce, _ = self.cluster_throttle_ctr.check_throttled(pod, False)
                except Exception:
                    errors.append(pod.key)
                    continue
                schedulable[pod.key] = not (ta or ti or te or ca or ci or ce)
            return {"schedulable": schedulable, "errors": errors}

    # dedupe is only attempted below this pod count: fingerprinting is
    # O(P) host work, and past this scale the fused device kernel wins
    # even against a perfectly degenerate mix
    BATCH_DEDUPE_MAX_PODS = 50_000

    def _pre_filter_batch_dedupe(self, known_ns: set) -> Optional[dict]:
        """Grouped batch triage: pods sharing a verdict fingerprint —
        (request-shape id, accel class, matched-cols of both kinds) — get
        ONE side-effect-free representative evaluation (the verdict is a
        pure function of the fingerprint, the same argument the cache
        rests on), cache-probed first and inserted after under the
        validate-after-compute protocol. Returns None to decline (caller
        falls through to the fused device path): mix not degenerate
        enough, or too many pods to fingerprint host-side.

        Semantics mirror the host-oracle fallback exactly: side-effect-free
        (no Warning events), unknown-namespace pods land in ``errors``,
        ERROR evaluations route every group member to ``errors``."""
        dm, cache = self.device_manager, self.verdict_cache
        pods = self.listers.pods.list()
        if len(pods) > self.BATCH_DEDUPE_MAX_PODS:
            return None
        groups: dict = {}
        loners: list = []
        for pod in pods:
            fp = dm.verdict_fingerprint(pod)
            if fp is None:
                loners.append(pod)
                continue
            g = groups.get(fp[0])
            if g is None:
                groups[fp[0]] = g = (fp[1], [])
            g[1].append(pod)
        if len(pods) > 256 and len(groups) * 2 > len(pods):
            return None  # not degenerate — grouping bought nothing
        schedulable: dict = {}
        errors: list = []
        for key, (esum, members) in groups.items():
            status = cache.get(key, esum)
            if status is None:
                rep = members[0]
                status = self._pre_filter_uncached(rep, emit_events=False)
                if self._cacheable(status) and dm.verdict_fingerprint(rep) == (
                    key,
                    esum,
                ):
                    cache.put(key, esum, status)
            if status.code is StatusCode.ERROR:
                errors.extend(p.key for p in members)
            else:
                ok = status.code is StatusCode.SUCCESS
                for p in members:
                    schedulable[p.key] = ok
        for pod in loners:
            # no fingerprint ⇒ no arena (shouldn't happen here — the route
            # requires a device manager) or unknown namespace; mirror the
            # key-derived routing of _merge_verdicts
            if pod.namespace not in known_ns:
                errors.append(pod.key)
                continue
            try:
                ta, ti, te, _ = self.throttle_ctr.check_throttled(pod, False)
                ca, ci, ce, _ = self.cluster_throttle_ctr.check_throttled(pod, False)
            except Exception:
                errors.append(pod.key)
                continue
            schedulable[pod.key] = not (ta or ti or te or ca or ci or ce)
        return {"schedulable": schedulable, "errors": errors}

    @staticmethod
    def _merge_verdicts(per_kind: dict, known_ns: set):
        """AND the per-kind schedulable verdicts per pod, then route pods of
        unknown namespaces to errors (the per-pod path returns ERROR for
        them, clusterthrottle_controller.go:273-276 — the batch surfaces
        must never report them schedulable). Shared by pre_filter_batch and
        full_tick_sharded so the two surfaces cannot drift.

        Merge shape: the first kind's verdicts build the result dict in one
        C-speed ``dict(zip(...))``; later kinds only FLIP the rows they
        block (np.nonzero of the inverted verdicts — blocked pods are the
        sparse case) plus a subset check for pods the first kind didn't
        carry. The former per-pod Python AND (2×100k dict ops) measured
        ~60ms of every full-scale batch call. The namespace routing stays
        key-derived (one partition per verdict key): deriving it from the
        pod informer's namespace index instead would make the
        never-schedulable invariant timing-dependent — a pod the device
        mirror has seen but the pod informer has not yet indexed would
        slip through."""
        import numpy as np

        schedulable: dict = {}
        errors: list = []
        for j, (ok, rows) in enumerate(per_kind.values()):
            # one vectorized gather per kind instead of a scalar numpy
            # index per pod (ok[row] costs ~µs each; at 100k pods the
            # per-item form dominated the whole batch call)
            ok = np.asarray(ok)
            idx = np.fromiter(rows.values(), dtype=np.int64, count=len(rows))
            vals = ok[idx]
            if j == 0:
                schedulable = dict(zip(rows.keys(), vals.tolist()))
                continue
            keys_list = None  # built only when this kind changes anything
            blocked = np.nonzero(~vals)[0]
            if blocked.size:
                keys_list = list(rows.keys())
                for i in blocked.tolist():
                    schedulable[keys_list[i]] = False
            if not (rows.keys() <= schedulable.keys()):  # C-speed subset probe
                if keys_list is None:
                    keys_list = list(rows.keys())
                for k, v in zip(keys_list, vals.tolist()):
                    if k not in schedulable:
                        schedulable[k] = v
        bad = [k for k in schedulable if k.partition("/")[0] not in known_ns]
        for key in bad:
            del schedulable[key]
            errors.append(key)
        return schedulable, errors

    def _apply_accel_class_overrides(self, schedulable: dict, errors: list) -> None:
        """Accel-class resolution on the batch-triage surfaces: the device
        planes carry only BASE thresholds, so a device-classified verdict
        for a pod whose accel class any mirrored throttle names is wrong
        whenever the per-class replacement differs. Route exactly those
        pods through the class-aware host oracle — the same route the
        single-pod ``check_throttled`` takes (PR 7) — and overwrite their
        rows in place. No accel thresholds mirrored ⇒ zero cost; otherwise
        cost is O(accel-class pods), not O(P)."""
        dm = self.device_manager
        if dm is None or not (
            dm.has_accel_thresholds("throttle")
            or dm.has_accel_thresholds("clusterthrottle")
        ):
            return
        from ..api.pod import accel_class_of

        for pod in self.listers.pods.list():
            if not accel_class_of(pod) or pod.key not in schedulable:
                continue
            try:
                ta, ti, te, _ = self.throttle_ctr.check_throttled(pod, False)
                ca, ci, ce, _ = self.cluster_throttle_ctr.check_throttled(pod, False)
            except Exception:
                del schedulable[pod.key]
                errors.append(pod.key)
                continue
            schedulable[pod.key] = not (ta or ti or te or ca or ci or ce)

    def full_tick_sharded(self, n_devices: Optional[int] = None, shape=None) -> dict:
        """The fused reconcile+PreFilter sweep over a device mesh — the
        multi-chip serving surface. Builds a 2D ("pods","throttles") Mesh
        over the first ``n_devices`` (default: all visible devices; one
        chip degenerates to a 1×1 mesh) and runs both kinds' complete
        tick tiled across it (DeviceStateManager.full_tick_sharded):
        override-resolved thresholds, used re-aggregation, throttled
        flags, and the [P,T] classification, with two psum all-reduces of
        tile partials as the only cross-device traffic.

        Returns ``{"schedulable": {pod_key: bool}, "used": {kind:
        {throttle_key: pod_count}}, "mesh": [dp, tp], "errors": [...]}``.
        Unlike ``pre_filter_batch`` this classifies against the
        freshly-derived state, not the written statuses (ahead of them
        under churn).
        """
        import numpy as np

        from ..parallel.mesh import make_mesh

        if self.device_manager is None:
            raise RuntimeError("full_tick_sharded requires the device data plane")
        with self.tracer.trace("full_tick"):
            mesh = make_mesh(n_devices, tuple(shape) if shape else None)
            known_ns = {ns.name for ns in self.listers.namespaces.list()}
            used: dict = {}
            out = self.device_manager.full_tick_sharded(mesh, on_equal=False)
            for kind, (_, _, _, used_cnt, _, col_map) in out.items():
                used[kind] = {
                    tkey: int(used_cnt[col]) for col, tkey in col_map.items()
                }
            schedulable, errors = self._merge_verdicts(
                {k: (v[1], v[2]) for k, v in out.items()}, known_ns
            )
            # accel-class pods resolve per-class thresholds host-side, the
            # documented accel route (their verdicts then read the written
            # statuses, like every accel check since PR 7 — the tick's
            # ahead-of-status freshness applies to base-threshold pods)
            self._apply_accel_class_overrides(schedulable, errors)
            return {
                "schedulable": schedulable,
                "used": used,
                "mesh": [mesh.shape["pods"], mesh.shape["throttles"]],
                "errors": errors,
            }

    # ---------------------------------------------------------------- reserve

    def reserve(self, pod: Pod, node: str = "") -> Status:
        with self.tracer.trace("reserve"):
            return self._reserve(pod, node)

    def _reserve(self, pod: Pod, node: str = "") -> Status:
        errs: List[str] = []
        try:
            self.throttle_ctr.reserve(pod)
        except Exception as e:
            errs.append(f"Failed to reserve pod={pod.key} in ThrottleController: {e}")
        try:
            self.cluster_throttle_ctr.reserve(pod)
        except Exception as e:
            errs.append(f"Failed to reserve pod={pod.key} in ClusterThrottleController: {e}")
        if errs:
            return Status(StatusCode.ERROR, tuple(errs))
        return Status(StatusCode.SUCCESS)

    def unreserve(self, pod: Pod, node: str = "") -> None:
        with self.tracer.trace("unreserve"):
            self._unreserve(pod, node)

    def _unreserve(self, pod: Pod, node: str = "") -> None:
        try:
            self.throttle_ctr.unreserve(pod)
        except Exception:
            logger.exception("Failed to unreserve pod %s in ThrottleController", pod.key)
        try:
            self.cluster_throttle_ctr.unreserve(pod)
        except Exception:
            logger.exception("Failed to unreserve pod %s in ClusterThrottleController", pod.key)

    # -------------------------------------------------------- gang admission

    def pre_filter_gang(self, group_key: str, pods: Sequence[Pod]) -> Status:
        """All-or-nothing group feasibility: does the WHOLE group fit under
        every matched throttle of both kinds simultaneously? The device
        path is ONE batched dispatch (DeviceStateManager.gang_check_groups
        → ops/gang_check.gang_check_both); the host fallback (no device /
        breaker open) is the sequential per-pod oracle the kernel is
        property-tested against. Per-member reasons come from the oracle;
        the device path reports blocking throttle keys per kind."""
        import time as _time

        t0 = _time.monotonic()
        try:
            with self.tracer.trace("prefilter_gang"):
                return self._pre_filter_gang(group_key, pods)
        finally:
            if self._gang_check_hist is not None:
                self._gang_check_hist.observe_key((), _time.monotonic() - t0)

    def _pre_filter_gang(self, group_key: str, pods: Sequence[Pod]) -> Status:
        from ..api.pod import accel_class_of
        from ..engine.gang import sequential_gang_check

        if not pods:
            return Status(StatusCode.SUCCESS)
        accel = next((c for c in map(accel_class_of, pods) if c), None)
        dm = self.device_manager
        if dm is not None:
            out = dm.guarded(
                "gang", dm.gang_check_groups, [(group_key, list(pods), accel)]
            )
            if out is not None:
                verdict = out[group_key]
                if verdict["ok"]:
                    return Status(StatusCode.SUCCESS)
                reasons: List[str] = []
                for kind in ("clusterthrottle", "throttle"):
                    detail = verdict["kinds"][kind]
                    if detail["exceeds"]:
                        reasons.append(f"gang:{kind}[pod-requests-exceeds-threshold]")
                    if detail["active"]:
                        reasons.append(f"gang:{kind}[active]")
                    if detail["blocked"]:
                        reasons.append(
                            f"gang:{kind}[group-insufficient]="
                            + ",".join(sorted(detail["blocked"]))
                        )
                vlog(2, "gang %s is unschedulable: %s", group_key, "; ".join(reasons))
                return Status(StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE, tuple(reasons))
        try:
            feasible, blocked = sequential_gang_check(
                pods,
                (
                    ("throttle", self.throttle_ctr, False),
                    ("clusterthrottle", self.cluster_throttle_ctr, False),
                ),
            )
        except Exception as e:
            return Status(StatusCode.ERROR, (str(e),))
        if feasible:
            return Status(StatusCode.SUCCESS)
        reasons = tuple(
            f"gang:{pod_key}: " + "; ".join(blocks)
            for pod_key, blocks in sorted(blocked.items())
        )
        vlog(2, "gang %s is unschedulable: %s", group_key, "; ".join(reasons))
        return Status(StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE, reasons)

    def reserve_gang(self, group_key: str, pods: Sequence[Pod]) -> Status:
        """Atomic multi-pod Reserve: every member on every matched throttle
        of both kinds, or nothing (engine/gang.py). The scheduler calls
        this once per admitted group instead of N per-pod reserves."""
        with self.tracer.trace("reserve_gang"):
            member_keys = {}
            try:
                for pod in pods:
                    member_keys[pod.key] = {
                        "throttle": self.throttle_ctr.affected_throttle_keys(pod),
                        "clusterthrottle": (
                            self.cluster_throttle_ctr.affected_cluster_throttle_keys(pod)
                        ),
                    }
            except Exception as e:
                return Status(
                    StatusCode.ERROR,
                    (f"Failed to resolve gang {group_key} member throttles: {e}",),
                )
            try:
                ok = self.gang.reserve_group(group_key, list(pods), member_keys)
            except Exception as e:
                return Status(
                    StatusCode.ERROR, (f"Failed to reserve gang {group_key}: {e}",)
                )
            if not ok:
                return Status(
                    StatusCode.ERROR,
                    (f"gang {group_key}: member reserve failed (rolled back)",),
                )
            return Status(StatusCode.SUCCESS)

    def unreserve_gang(self, group_key: str) -> None:
        """Release the whole group reserve (scheduler Unreserve analog)."""
        with self.tracer.trace("unreserve_gang"):
            try:
                self.gang.rollback_group(group_key, "unreserve")
            except Exception:
                logger.exception("Failed to unreserve gang %s", group_key)

    # ----------------------------------------------------- policy / preempt

    def _policy_flip_priority(self, ctr):
        """Per-key hi-lane promotion priority for ``ctr``'s flips: the
        policy weight margin of the throttle's declared accel classes
        (PolicySpec.promotion_priority). Zero — the original FIFO lane —
        for throttles with no classes, unknown keys, or a weightless
        policy, so the default path is byte-identical."""

        def fn(key: str) -> int:
            spec = self.policy.active()
            if not spec.class_weights:
                return 0  # weightless policy: skip the store lookup entirely
            try:
                thr = ctr.throttle_by_key(key)
            except Exception:
                return 0
            classes = [
                e.accel_class for e in thr.spec.accel_class_thresholds
            ]
            if not classes:
                return 0
            return spec.promotion_priority(classes)

        return fn

    def set_policy_specs(self, specs) -> int:
        """Hot-swap the whole policy (the temporaryThresholdOverrides
        discipline applied to policy-as-data): accepts PolicySpec objects
        or their dict wire form. Returns the new policy generation."""
        from ..policy.spec import PolicySpec, policy_spec_from_dict

        decoded = [
            s if isinstance(s, PolicySpec) else policy_spec_from_dict(s)
            for s in specs
        ]
        gen = self.policy.set_specs(decoded)
        # policy swaps reach verdicts through reconcile status writes
        # (epoch-covered), but drop everything eagerly anyway — a swap is
        # rare and the repopulation cost is one miss per live key
        if self.verdict_cache is not None:
            self.verdict_cache.invalidate_all()
        return gen

    def maybe_preempt_gang(self, group_key: str, pods: Sequence[Pod]) -> bool:
        """Gang-aware preemption entry (scheduler._schedule_gang calls
        this after a capacity rejection): one coordinator cycle — policy
        gate → deficits → ranked victim selection (batched kernel ≡
        sequential oracle) → journaled, gang-atomic delete-then-requeue
        eviction. True iff victims were evicted (the freed capacity's
        requeue hints will re-drive the group)."""
        with self.tracer.trace("preempt"):
            try:
                report = self.preempt.preempt_for_gang(group_key, list(pods))
            except Exception:
                logger.exception("preemption cycle failed for gang %s", group_key)
                return False
            return report["evicted"] > 0

    # ----------------------------------------------------------------- events

    def events_to_register(self) -> Sequence[ClusterEvent]:
        return (
            ClusterEvent("Node"),
            ClusterEvent("Pod"),
            ClusterEvent(f"throttles.{SCHEME_VERSION}.{SCHEME_GROUP}"),
            ClusterEvent(f"clusterthrottles.{SCHEME_VERSION}.{SCHEME_GROUP}"),
        )

    def pre_filter_extensions(self) -> None:
        return None  # plugin.go:259-261

    # ---------------------------------------------------------------- control

    def start(self) -> None:
        self.throttle_ctr.start()
        self.cluster_throttle_ctr.start()

    def stop(self) -> None:
        self.throttle_ctr.stop()
        self.cluster_throttle_ctr.stop()
        self.informer_factory.shutdown()
        self.core_informer_factory.shutdown()

    def run_pending_once(self) -> int:
        """Deterministic single-threaded drain (tests / embedding)."""
        return self.throttle_ctr.run_pending_once() + self.cluster_throttle_ctr.run_pending_once()
