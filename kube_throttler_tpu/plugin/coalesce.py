"""Micro-batching pre_filter front-end (leader-follower coalescing).

Concurrent ``pre_filter`` callers each pay a full device dispatch+sync for
a 1-pod kernel — the dominant slice of the per-decision latency (~30-40µs
on CPU, more through a TPU tunnel), and the reason thread-scaling of the
naive path flatlines (VERDICT r3/r4: 4 threads ≤ 1 thread). The reference
has no analog: its PreFilter is pure in-memory Go (plugin.go:148-215) and
scales with goroutines; ours pays a kernel dispatch, so the fix is to
AMORTIZE it.

Leader-follower batching (the classic group-commit shape): the first
caller in an empty window becomes the leader, sleeps ``window_s`` to let
concurrent followers enqueue, then issues ONE fused [B,K] gather dispatch
per kind (``DeviceStateManager.check_pods_multi``) for the whole batch and
distributes per-pod classification maps. Every pod's Status is then
composed through exactly the same controller/reason code as the direct
path (``classify_from_map`` → ``_compose_prefilter_status``), so semantics
— reason strings, ordering, Warning events — are identical.

Sizing guidance: the default ``window_s=0`` is NATURAL batching — the
leader takes whatever queued while the previous leader's dispatch ran, so
no timer latency is ever added and a lone caller pays exactly the direct
path's cost. A positive window trades added latency for bigger batches
(useful when callers arrive in bursts sparser than a dispatch width);
keep it well under the BASELINE <1ms p99 target. ``max_batch`` bounds the
fused shape (B pads to ladder rungs, so compiled-shape count stays
logarithmic). For BULK triage of the whole stored pod set, use
``plugin.pre_filter_batch`` — that is the official scaling surface for
sweep-shaped loads; the coalescer serves interactive scheduler traffic.

Measured verdict (single-core CPU host, r5 bench): coalescing LOSES there
— ~0.4× of 1-thread direct — because each follower pays two context
switches (~150µs under load on one core) to save a ~40µs CPU dispatch,
while naive GIL-serialized threads pay no coordination at all. The
crossover needs (a) dispatch cost ≫ wakeup cost — true through a TPU
tunnel, where a dispatch is ~ms — or (b) real cores for followers to
wait on. Deployments on the TPU serving path should enable it; pure-CPU
single-core deployments should not. The bench records both numbers
(served_decisions_per_sec_4t vs _4t_coalesced) so the tradeoff is visible
per platform.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from ..api.pod import Pod
from ..utils.lockorder import guard_attrs, make_lock
from .framework import Status, StatusCode


class _Entry:
    __slots__ = ("pod", "event", "status")

    def __init__(self, pod: Pod) -> None:
        self.pod = pod
        self.event = threading.Event()
        self.status: Optional[Status] = None


@guard_attrs
class PreFilterCoalescer:
    GUARDED_BY = {
        "_queue": "self._lock",
        "_leader_active": "self._lock",
    }

    def __init__(self, plugin, window_s: float = 0.0, max_batch: int = 64):
        self._plugin = plugin
        self._window = window_s
        self._max_batch = max_batch
        self._lock = make_lock("plugin.coalescer")
        self._queue: List[_Entry] = []
        self._leader_active = False

    def pre_filter(self, pod: Pod) -> Status:
        dm = self._plugin.device_manager
        if dm is None:
            return self._plugin.pre_filter(pod)
        entry = _Entry(pod)
        with self._lock:
            self._queue.append(entry)
            lead = not self._leader_active
            if lead:
                self._leader_active = True
        if not lead:
            entry.event.wait()
            # a follower whose batch overflowed max_batch is re-led below
            if entry.status is not None:
                return entry.status
            return self._plugin.pre_filter(pod)
        if self._window > 0:
            time.sleep(self._window)  # collect followers (yields the GIL too)
        with self._lock:
            batch = self._queue[: self._max_batch]
            overflow = self._queue[self._max_batch :]
            self._queue = []
            self._leader_active = False
        try:
            self._classify_batch(batch)
        finally:
            for e in batch:
                if e.status is None:
                    e.status = None  # falls back in the waiter
                e.event.set()
            for e in overflow:
                # overflow entries re-run individually (rare: >max_batch
                # concurrent callers inside one window)
                e.event.set()
        return entry.status if entry.status is not None else self._plugin.pre_filter(pod)

    def _classify_batch(self, batch: List[_Entry]) -> None:
        plugin = self._plugin
        dm = plugin.device_manager
        pods = [e.pod for e in batch]
        try:
            thr_maps = dm.guarded("check", dm.check_pods_multi, pods, "throttle")
            clthr_maps = dm.guarded(
                "check", dm.check_pods_multi, pods, "clusterthrottle"
            )
        except Exception:
            thr_maps = clthr_maps = None
        if thr_maps is None or clthr_maps is None:
            return  # breaker open / dispatch failed: waiters fall back
        for e, tmap, cmap in zip(batch, thr_maps, clthr_maps):
            try:
                # the cluster kind's missing-namespace contract
                # (clusterthrottle_controller.go:273-276) holds here too
                if plugin.cluster_throttle_ctr._get_namespace(e.pod.namespace) is None:
                    e.status = Status(
                        StatusCode.ERROR,
                        (f"namespace {e.pod.namespace!r} not found",),
                    )
                    continue
                thr4 = plugin.throttle_ctr.classify_from_map(tmap)
                clthr4 = plugin.cluster_throttle_ctr.classify_from_map(cmap)
                e.status = plugin._compose_prefilter_status(e.pod, thr4, clthr4)
            except Exception as exc:  # per-pod decode error → per-pod status
                e.status = Status(StatusCode.ERROR, (str(exc),))
