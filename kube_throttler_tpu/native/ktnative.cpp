// Native selector row-match engine for the TPU throttler's host control plane.
//
// Role (reference parity): the reference's affectedThrottles is a linear Go
// scan of every Throttle's selector per pod event (throttle_controller.go:
// 248-269, clusterthrottle_controller.go:272-298).  The Python index
// (kube_throttler_tpu/engine/index.py) materializes the [P,T] mask and
// recomputes one row per pod event; this library moves that row recompute —
// the only O(#throttles) scalar loop left on the host — into C++.
//
// Model: Python keeps authority over interning (label keys/values/namespaces
// → int32 ids), row/column allocation, and the general tier (selectors
// whose validation fails — exact error-confinement semantics stay in
// Python).  Each throttle column is compiled here to its selector terms
// (selector.selecterTerms[] OR-ed, each term an AND of requirements —
// throttle_selector.go:30-54; ClusterThrottle terms additionally AND a
// namespaceSelector, clusterthrottle_selector.go:112-141).  Requirements
// carry an operator: Eq (matchLabels) plus the full matchExpressions set
// In / NotIn / Exists / DoesNotExist (metav1.LabelSelectorRequirement).
// ktn_match_row evaluates one pod in a single call; columns flagged
// general are evaluated back in Python.
//
// Semantics mirrored exactly (see SelectorIndex._match_one):
//   - namespaced Throttle: pod.namespace must equal the throttle's namespace
//     (applies to general columns too — the gate short-circuits them).
//   - ClusterThrottle: a pod whose Namespace object is unknown never matches
//     (clusterthrottle_controller.go:273-276).
//   - OR of zero terms is false (empty selector matches nothing); a term
//     with zero requirements matches everything.
//
// Candidate pruning: a matchLabels term with at least one pod requirement
// can only match a pod that carries the term's FIRST (key,value) pair
// exactly, so columns are inverted-indexed by that pair.  ktn_match_row
// then evaluates only the columns reachable from the pod's own label pairs
// (plus an "always" list: general columns and terms with no pod
// requirements) instead of scanning all T columns.  Term EVALUATION drops
// from O(T) to O(candidates); the output buffers are still zeroed in O(T)
// per call (byte memsets — cheap constants that the [T]-sized output ABI
// requires).
//
// C ABI only (loaded via ctypes); no exceptions cross the boundary.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

// operator codes (shared contract with native/__init__.py)
enum Op : int32_t {
  OP_EQ = 0,             // matchLabels entry: label == vals[0]
  OP_IN = 1,             // label present and ∈ vals
  OP_NOT_IN = 2,         // label absent, or ∉ vals
  OP_EXISTS = 3,         // key present
  OP_DOES_NOT_EXIST = 4, // key absent
};

struct Req {
  int32_t key;
  int32_t op;
  std::vector<int32_t> vals;  // Eq: 1 entry; In/NotIn: ≥1; Exists/DNE: empty
};

struct Term {
  std::vector<Req> pod;  // pod-label requirements
  std::vector<Req> ns;   // namespace-label requirements (ClusterThrottle only)
};

struct Col {
  bool valid = false;
  bool general = false;  // evaluated by the Python general tier
  bool in_always = false;
  int32_t thr_ns = -1;   // required pod-namespace id (namespaced Throttle); -1 = cluster
  std::vector<Term> terms;
  std::vector<uint64_t> bucket_keys;  // inverted-index keys this col occupies
};

struct Engine {
  bool cluster = false;  // kind == clusterthrottle
  std::vector<Col> cols;
  // (key,value) pair of a term's first pod requirement → candidate columns
  std::unordered_map<uint64_t, std::vector<int32_t>> buckets;
  std::vector<int32_t> always;  // general cols + terms with no pod reqs
  std::vector<int64_t> stamp;   // per-col visited epoch (query-time dedup)
  int64_t epoch = 0;
};

uint64_t bucket_key(int32_t k, int32_t v) {
  return (static_cast<uint64_t>(static_cast<uint32_t>(k)) << 32) |
         static_cast<uint32_t>(v);
}

void unindex_col(Engine* e, int32_t c) {
  Col& col = e->cols[c];
  for (uint64_t k : col.bucket_keys) {
    auto it = e->buckets.find(k);
    if (it == e->buckets.end()) continue;
    auto& v = it->second;
    v.erase(std::remove(v.begin(), v.end(), c), v.end());
    if (v.empty()) e->buckets.erase(it);
  }
  col.bucket_keys.clear();
  if (col.in_always) {
    e->always.erase(std::remove(e->always.begin(), e->always.end(), c),
                    e->always.end());
    col.in_always = false;
  }
}

bool has_exact_req(const Term& t) {
  for (const Req& r : t.pod)
    if (r.op == OP_EQ || (r.op == OP_IN && r.vals.size() == 1)) return true;
  return false;
}

void index_col(Engine* e, int32_t c) {
  Col& col = e->cols[c];
  if (!col.valid) return;
  // a term with no EXACT pod requirement (Eq / single-value In) cannot be
  // bucketed by value — multi-In/NotIn/Exists/DoesNotExist/ns-only terms
  // must be evaluated for every pod
  bool always = col.general;
  for (const Term& t : col.terms) {
    if (!has_exact_req(t)) always = true;
  }
  if (always) {
    e->always.push_back(c);
    col.in_always = true;
    return;  // evaluated unconditionally — bucket entries would be dead
  }
  for (const Term& t : col.terms) {
    // bucket by the term's first EXACT pod requirement (Eq, or In with one
    // value): a pod lacking that (key,value) provably fails the term.
    // Every term has one here — termless/inexact terms joined the always
    // list above and returned.
    for (const Req& r : t.pod) {
      bool exact = (r.op == OP_EQ) || (r.op == OP_IN && r.vals.size() == 1);
      if (!exact) continue;
      uint64_t k = bucket_key(r.key, r.vals[0]);
      auto& v = e->buckets[k];
      if (std::find(v.begin(), v.end(), c) == v.end()) v.push_back(c);
      col.bucket_keys.push_back(k);
      break;
    }
  }
}

// All requirements satisfied by the (keys,vals) label set?  Label sets are
// small (a handful of entries), so a linear probe beats hashing.
// Semantics mirror LabelSelector.matches (api/types.py:303-322).
bool pairs_match(const std::vector<Req>& reqs, const int32_t* keys,
                 const int32_t* vals, int32_t n) {
  for (const Req& r : reqs) {
    int32_t label_val = 0;
    bool present = false;
    for (int32_t i = 0; i < n; ++i) {
      if (keys[i] == r.key) {
        present = true;
        label_val = vals[i];
        break;
      }
    }
    switch (r.op) {
      case OP_EQ:
        if (!present || label_val != r.vals[0]) return false;
        break;
      case OP_IN: {
        if (!present) return false;
        bool in = false;
        for (int32_t v : r.vals)
          if (v == label_val) { in = true; break; }
        if (!in) return false;
        break;
      }
      case OP_NOT_IN: {
        if (present) {
          for (int32_t v : r.vals)
            if (v == label_val) return false;
        }
        break;
      }
      case OP_EXISTS:
        if (!present) return false;
        break;
      case OP_DOES_NOT_EXIST:
        if (present) return false;
        break;
      default:
        return false;  // unknown op never compiles; defensive
    }
  }
  return true;
}

}  // namespace

extern "C" {

void* ktn_create(int32_t is_cluster) {
  Engine* e = new Engine();
  e->cluster = (is_cluster != 0);
  return e;
}

void ktn_destroy(void* h) { delete static_cast<Engine*>(h); }

void ktn_reserve(void* h, int32_t tcap) {
  Engine* e = static_cast<Engine*>(h);
  if (static_cast<int32_t>(e->cols.size()) < tcap) e->cols.resize(tcap);
}

namespace {
// Decode one side's nested CSR: term t's requirements are indices
// [term_off[t], term_off[t+1]) into (req_key, req_op, req_voff); each
// requirement r's values are req_vals[req_voff[r]..req_voff[r+1]).
void decode_reqs(std::vector<Req>* out, int32_t t, const int32_t* term_off,
                 const int32_t* req_key, const int32_t* req_op,
                 const int32_t* req_voff, const int32_t* req_vals) {
  for (int32_t r = term_off[t]; r < term_off[t + 1]; ++r) {
    Req req;
    req.key = req_key[r];
    req.op = req_op[r];
    for (int32_t v = req_voff[r]; v < req_voff[r + 1]; ++v)
      req.vals.push_back(req_vals[v]);
    out->push_back(std::move(req));
  }
}
}  // namespace

// Compile a column.  Both selector sides arrive as nested CSR (see
// decode_reqs); operator codes per the Op enum.
void ktn_set_col(void* h, int32_t col, int32_t thr_ns, int32_t n_terms,
                 const int32_t* pod_term_off, const int32_t* pod_req_key,
                 const int32_t* pod_req_op, const int32_t* pod_req_voff,
                 const int32_t* pod_req_vals, const int32_t* ns_term_off,
                 const int32_t* ns_req_key, const int32_t* ns_req_op,
                 const int32_t* ns_req_voff, const int32_t* ns_req_vals) {
  Engine* e = static_cast<Engine*>(h);
  if (col >= static_cast<int32_t>(e->cols.size())) e->cols.resize(col + 1);
  unindex_col(e, col);
  Col& c = e->cols[col];
  c.valid = true;
  c.general = false;
  c.thr_ns = thr_ns;
  c.terms.clear();
  c.terms.reserve(n_terms);
  for (int32_t t = 0; t < n_terms; ++t) {
    Term term;
    decode_reqs(&term.pod, t, pod_term_off, pod_req_key, pod_req_op,
                pod_req_voff, pod_req_vals);
    decode_reqs(&term.ns, t, ns_term_off, ns_req_key, ns_req_op, ns_req_voff,
                ns_req_vals);
    c.terms.push_back(std::move(term));
  }
  index_col(e, col);
}

// Column whose selector needs the Python general tier (selectors that fail
// validation — exact error-confinement semantics stay in Python; valid
// matchExpressions compile natively via ktn_set_col).  The namespace gate
// still applies natively.
void ktn_set_col_general(void* h, int32_t col, int32_t thr_ns) {
  Engine* e = static_cast<Engine*>(h);
  if (col >= static_cast<int32_t>(e->cols.size())) e->cols.resize(col + 1);
  unindex_col(e, col);
  Col& c = e->cols[col];
  c.valid = true;
  c.general = true;
  c.thr_ns = thr_ns;
  c.terms.clear();
  index_col(e, col);
}

void ktn_clear_col(void* h, int32_t col) {
  Engine* e = static_cast<Engine*>(h);
  if (col < static_cast<int32_t>(e->cols.size())) {
    unindex_col(e, col);
    e->cols[col] = Col{};
  }
}

int32_t ktn_num_cols(void* h) {
  return static_cast<int32_t>(static_cast<Engine*>(h)->cols.size());
}

// Evaluate one pod against all compiled columns.
//   pod_ns     — interned namespace id of the pod
//   ns_exists  — 1 iff the Namespace object is known (ClusterThrottle gate)
//   (pk,pv,np) — interned pod-label (key,value) pairs
//   (nk,nv,nn) — interned namespace-label pairs of the pod's namespace
//   out[c]         — 1 iff column c matches (0 for general columns)
//   general_out[c] — 1 iff Python must evaluate column c (gate passed)
// Both outputs must hold ktn_num_cols entries.
void ktn_match_row(void* h, int32_t pod_ns, int32_t ns_exists,
                   const int32_t* pk, const int32_t* pv, int32_t np,
                   const int32_t* nk, const int32_t* nv, int32_t nn,
                   uint8_t* out, uint8_t* general_out) {
  Engine* e = static_cast<Engine*>(h);
  const int32_t T = static_cast<int32_t>(e->cols.size());
  std::memset(out, 0, T);
  std::memset(general_out, 0, T);
  if (static_cast<int32_t>(e->stamp.size()) < T) e->stamp.resize(T, 0);
  const int64_t epoch = ++e->epoch;

  auto eval = [&](int32_t c) {
    if (e->stamp[c] == epoch) return;  // already evaluated this call
    e->stamp[c] = epoch;
    const Col& col = e->cols[c];
    if (!col.valid) return;
    if (!e->cluster) {
      if (col.thr_ns != pod_ns) return;
    } else if (!ns_exists) {
      return;
    }
    if (col.general) {
      general_out[c] = 1;
      return;
    }
    for (const Term& t : col.terms) {
      if (!pairs_match(t.pod, pk, pv, np)) continue;
      if (e->cluster && !pairs_match(t.ns, nk, nv, nn)) continue;
      out[c] = 1;
      break;
    }
  };

  // candidates: columns whose bucketing pair the pod actually carries,
  // plus the always list (general columns / no-pod-requirement terms) —
  // a term's first requirement unmatched ⇒ the term cannot match, so
  // non-candidates are provably non-matching
  for (int32_t c : e->always) eval(c);
  for (int32_t i = 0; i < np; ++i) {
    auto it = e->buckets.find(bucket_key(pk[i], pv[i]));
    if (it == e->buckets.end()) continue;
    for (int32_t c : it->second) eval(c);
  }
}

// ---------------------------------------------------------------------------
// Single-pod 4-step classification over K gathered throttle columns — the
// native tier of the serving hot path (devicestate.check_pod's host route on
// accelerator backends, where a per-decision device dispatch would cost a
// full tunnel round trip).  Semantics are a line-for-line mirror of
// devicestate._host_classify_rows / ops.check._classify_core (reference
// check_throttled_for, throttle_types.go:128-153):
//   1. pod alone exceeds threshold        → 3 (POD_EXCEEDS; onEqual=false)
//   2. persisted status.throttled flags   → 1 (ACTIVE)
//   3. used+reserved saturates threshold  → 1 (ACTIVE; step3_on_equal)
//   4. used+reserved+pod overflows        → 2 (INSUFFICIENT; on_equal)
//   else                                  → 0 (NOT_THROTTLED)
// Invalid columns (thr_valid=0) report -1 (NOT_AFFECTED).  Presence-mask
// algebra (absent ≠ zero, resource_amount.go:127-159) carried by the *_p
// byte arrays; a ~20-numpy-op Python pass measured ~50µs/kind per decision
// at 100k×10k, this loop runs the same K×R work in well under 1µs, so the
// caller may hold its snapshot lock across the call.
//
// Status codes are a shared contract with ops/check.py CHECK_* and the
// [T]/[T,R] state arrays are the row-major int64/bool staging planes of
// devicestate._KindState (second dim exactly R, C-contiguous).
//
// API shape: plane pointers are REGISTERED once into a handle
// (ktn_cls_create) and re-registered only when Python reallocates a
// staging array (capacity growth — logarithmic under the ladder policy).
// A flat per-call signature was measured first: 22 ctypes data_as
// conversions cost ~50µs/call in marshaling alone, erasing the win; the
// handle form leaves 8 scalar args ≈ µs-scale.

struct ClsPlanes {
  int32_t R;
  const uint8_t* thr_valid;
  const int64_t* thr_cnt; const uint8_t* thr_cnt_p;
  const int64_t* thr_req; const uint8_t* thr_req_p;
  const uint8_t* st_cnt; const uint8_t* st_fp; const uint8_t* st_t;
  const int64_t* used_cnt; const uint8_t* used_cnt_p;
  const int64_t* used_req; const uint8_t* used_req_p;
  const int64_t* res_cnt; const uint8_t* res_cnt_p;
  const int64_t* res_req; const uint8_t* res_req_p;
};

void* ktn_cls_create(
    int32_t R,
    const uint8_t* thr_valid,
    const int64_t* thr_cnt, const uint8_t* thr_cnt_p,
    const int64_t* thr_req, const uint8_t* thr_req_p,
    const uint8_t* st_cnt, const uint8_t* st_fp, const uint8_t* st_t,
    const int64_t* used_cnt, const uint8_t* used_cnt_p,
    const int64_t* used_req, const uint8_t* used_req_p,
    const int64_t* res_cnt, const uint8_t* res_cnt_p,
    const int64_t* res_req, const uint8_t* res_req_p) {
  return new ClsPlanes{R, thr_valid, thr_cnt, thr_cnt_p, thr_req, thr_req_p,
                       st_cnt, st_fp, st_t, used_cnt, used_cnt_p,
                       used_req, used_req_p, res_cnt, res_cnt_p,
                       res_req, res_req_p};
}

void ktn_cls_destroy(void* h) { delete static_cast<ClsPlanes*>(h); }

void ktn_cls_run(const void* h, int32_t K, const int32_t* cols,
                 const int64_t* pod_req, const uint8_t* pod_present,
                 int32_t on_equal, int32_t step3_on_equal, int8_t* out) {
  const ClsPlanes& p = *static_cast<const ClsPlanes*>(h);
  const int32_t R = p.R;
  auto cmp = [](int64_t u, int64_t t, bool oe) { return oe ? u >= t : u > t; };
  const bool oe = on_equal != 0, s3 = step3_on_equal != 0;
  for (int32_t k = 0; k < K; ++k) {
    const int32_t c = cols[k];
    if (!p.thr_valid[c]) {
      out[k] = -1;  // NOT_AFFECTED
      continue;
    }
    const int64_t off = static_cast<int64_t>(c) * R;
    const int64_t* trq = p.thr_req + off;
    const uint8_t* trp = p.thr_req_p + off;
    const uint8_t* sfp = p.st_fp + off;
    const uint8_t* sft = p.st_t + off;
    const int64_t* urq = p.used_req + off;
    const uint8_t* urp = p.used_req_p + off;
    const int64_t* rrq = p.res_req + off;
    const uint8_t* rrp = p.res_req_p + off;
    const int64_t au_cnt = p.used_cnt[c] + p.res_cnt[c];
    const bool au_cnt_present = p.used_cnt_p[c] || p.res_cnt_p[c];

    // step 1 (pod count is 1 and always present)
    bool exceeds = p.thr_cnt_p[c] && (1 > p.thr_cnt[c]);
    for (int32_t r = 0; !exceeds && r < R; ++r)
      exceeds = trp[r] && pod_present[r] && pod_req[r] > trq[r] && pod_req[r] != 0;
    if (exceeds) {
      out[k] = 3;  // POD_EXCEEDS
      continue;
    }
    // step 2
    bool active = p.st_cnt[c];
    for (int32_t r = 0; !active && r < R; ++r)
      active = sfp[r] && sft[r] && pod_present[r] && pod_req[r] != 0;
    // step 3
    if (!active)
      active = p.thr_cnt_p[c] && au_cnt_present && cmp(au_cnt, p.thr_cnt[c], s3);
    for (int32_t r = 0; !active && r < R; ++r)
      active = trp[r] && (urp[r] || rrp[r]) &&
               cmp(urq[r] + rrq[r], trq[r], s3) &&
               pod_present[r] && pod_req[r] != 0;
    if (active) {
      out[k] = 1;  // ACTIVE
      continue;
    }
    // step 4
    bool insufficient = p.thr_cnt_p[c] && cmp(au_cnt + 1, p.thr_cnt[c], oe);
    for (int32_t r = 0; !insufficient && r < R; ++r)
      insufficient = trp[r] && (urp[r] || rrp[r] || pod_present[r]) &&
                     cmp(urq[r] + rrq[r] + pod_req[r], trq[r], oe) &&
                     pod_present[r] && pod_req[r] != 0;
    out[k] = insufficient ? 2 : 0;  // INSUFFICIENT : NOT_THROTTLED
  }
}

}  // extern "C"
