// Native selector row-match engine for the TPU throttler's host control plane.
//
// Role (reference parity): the reference's affectedThrottles is a linear Go
// scan of every Throttle's selector per pod event (throttle_controller.go:
// 248-269, clusterthrottle_controller.go:272-298).  The Python index
// (kube_throttler_tpu/engine/index.py) materializes the [P,T] mask and
// recomputes one row per pod event; this library moves that row recompute —
// the only O(#throttles) scalar loop left on the host — into C++.
//
// Model: Python keeps authority over interning (label keys/values/namespaces
// → int32 ids), row/column allocation, and the general (matchExpressions)
// tier.  Each throttle column is compiled here to its matchLabels-only
// selector terms (selector.selecterTerms[] OR-ed, each term an AND of
// (key,value) requirements — throttle_selector.go:30-54; ClusterThrottle
// terms additionally AND a namespaceSelector, clusterthrottle_selector.go:
// 112-141).  ktn_match_row evaluates one pod against every column in a
// single call; columns that need the general tier are flagged back to
// Python instead of being evaluated here.
//
// Semantics mirrored exactly (see SelectorIndex._match_one):
//   - namespaced Throttle: pod.namespace must equal the throttle's namespace
//     (applies to general columns too — the gate short-circuits them).
//   - ClusterThrottle: a pod whose Namespace object is unknown never matches
//     (clusterthrottle_controller.go:273-276).
//   - OR of zero terms is false (empty selector matches nothing); a term
//     with zero requirements matches everything.
//
// Candidate pruning: a matchLabels term with at least one pod requirement
// can only match a pod that carries the term's FIRST (key,value) pair
// exactly, so columns are inverted-indexed by that pair.  ktn_match_row
// then evaluates only the columns reachable from the pod's own label pairs
// (plus an "always" list: general columns and terms with no pod
// requirements) instead of scanning all T columns.  Term EVALUATION drops
// from O(T) to O(candidates); the output buffers are still zeroed in O(T)
// per call (byte memsets — cheap constants that the [T]-sized output ABI
// requires).
//
// C ABI only (loaded via ctypes); no exceptions cross the boundary.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

struct Req {
  int32_t key;
  int32_t val;
};

struct Term {
  std::vector<Req> pod;  // pod-label requirements
  std::vector<Req> ns;   // namespace-label requirements (ClusterThrottle only)
};

struct Col {
  bool valid = false;
  bool general = false;  // evaluated by the Python general tier
  bool in_always = false;
  int32_t thr_ns = -1;   // required pod-namespace id (namespaced Throttle); -1 = cluster
  std::vector<Term> terms;
  std::vector<uint64_t> bucket_keys;  // inverted-index keys this col occupies
};

struct Engine {
  bool cluster = false;  // kind == clusterthrottle
  std::vector<Col> cols;
  // (key,value) pair of a term's first pod requirement → candidate columns
  std::unordered_map<uint64_t, std::vector<int32_t>> buckets;
  std::vector<int32_t> always;  // general cols + terms with no pod reqs
  std::vector<int64_t> stamp;   // per-col visited epoch (query-time dedup)
  int64_t epoch = 0;
};

uint64_t bucket_key(int32_t k, int32_t v) {
  return (static_cast<uint64_t>(static_cast<uint32_t>(k)) << 32) |
         static_cast<uint32_t>(v);
}

void unindex_col(Engine* e, int32_t c) {
  Col& col = e->cols[c];
  for (uint64_t k : col.bucket_keys) {
    auto it = e->buckets.find(k);
    if (it == e->buckets.end()) continue;
    auto& v = it->second;
    v.erase(std::remove(v.begin(), v.end(), c), v.end());
    if (v.empty()) e->buckets.erase(it);
  }
  col.bucket_keys.clear();
  if (col.in_always) {
    e->always.erase(std::remove(e->always.begin(), e->always.end(), c),
                    e->always.end());
    col.in_always = false;
  }
}

void index_col(Engine* e, int32_t c) {
  Col& col = e->cols[c];
  if (!col.valid) return;
  bool always = col.general;
  for (const Term& t : col.terms) {
    if (t.pod.empty()) always = true;
  }
  if (always) {
    e->always.push_back(c);
    col.in_always = true;
    return;  // evaluated unconditionally — bucket entries would be dead
  }
  for (const Term& t : col.terms) {
    if (t.pod.empty()) continue;
    uint64_t k = bucket_key(t.pod[0].key, t.pod[0].val);
    auto& v = e->buckets[k];
    if (std::find(v.begin(), v.end(), c) == v.end()) v.push_back(c);
    col.bucket_keys.push_back(k);
  }
}

// All requirements satisfied by the (keys,vals) label set?  Label sets are
// small (a handful of entries), so a linear probe beats hashing.
bool pairs_match(const std::vector<Req>& reqs, const int32_t* keys,
                 const int32_t* vals, int32_t n) {
  for (const Req& r : reqs) {
    bool ok = false;
    for (int32_t i = 0; i < n; ++i) {
      if (keys[i] == r.key) {
        ok = (vals[i] == r.val);
        break;
      }
    }
    if (!ok) return false;
  }
  return true;
}

}  // namespace

extern "C" {

void* ktn_create(int32_t is_cluster) {
  Engine* e = new Engine();
  e->cluster = (is_cluster != 0);
  return e;
}

void ktn_destroy(void* h) { delete static_cast<Engine*>(h); }

void ktn_reserve(void* h, int32_t tcap) {
  Engine* e = static_cast<Engine*>(h);
  if (static_cast<int32_t>(e->cols.size()) < tcap) e->cols.resize(tcap);
}

// Compile a matchLabels-only column.  Terms arrive flattened CSR-style:
// term t's pod requirements are (pod_keys,pod_vals)[pod_off[t]..pod_off[t+1])
// and its namespace requirements the same over ns_off/ns_keys/ns_vals.
void ktn_set_col(void* h, int32_t col, int32_t thr_ns, int32_t n_terms,
                 const int32_t* pod_off, const int32_t* pod_keys,
                 const int32_t* pod_vals, const int32_t* ns_off,
                 const int32_t* ns_keys, const int32_t* ns_vals) {
  Engine* e = static_cast<Engine*>(h);
  if (col >= static_cast<int32_t>(e->cols.size())) e->cols.resize(col + 1);
  unindex_col(e, col);
  Col& c = e->cols[col];
  c.valid = true;
  c.general = false;
  c.thr_ns = thr_ns;
  c.terms.clear();
  c.terms.reserve(n_terms);
  for (int32_t t = 0; t < n_terms; ++t) {
    Term term;
    for (int32_t i = pod_off[t]; i < pod_off[t + 1]; ++i)
      term.pod.push_back({pod_keys[i], pod_vals[i]});
    for (int32_t i = ns_off[t]; i < ns_off[t + 1]; ++i)
      term.ns.push_back({ns_keys[i], ns_vals[i]});
    c.terms.push_back(std::move(term));
  }
  index_col(e, col);
}

// Column whose selector needs the Python general tier (matchExpressions /
// parse errors).  The namespace gate still applies natively.
void ktn_set_col_general(void* h, int32_t col, int32_t thr_ns) {
  Engine* e = static_cast<Engine*>(h);
  if (col >= static_cast<int32_t>(e->cols.size())) e->cols.resize(col + 1);
  unindex_col(e, col);
  Col& c = e->cols[col];
  c.valid = true;
  c.general = true;
  c.thr_ns = thr_ns;
  c.terms.clear();
  index_col(e, col);
}

void ktn_clear_col(void* h, int32_t col) {
  Engine* e = static_cast<Engine*>(h);
  if (col < static_cast<int32_t>(e->cols.size())) {
    unindex_col(e, col);
    e->cols[col] = Col{};
  }
}

int32_t ktn_num_cols(void* h) {
  return static_cast<int32_t>(static_cast<Engine*>(h)->cols.size());
}

// Evaluate one pod against all compiled columns.
//   pod_ns     — interned namespace id of the pod
//   ns_exists  — 1 iff the Namespace object is known (ClusterThrottle gate)
//   (pk,pv,np) — interned pod-label (key,value) pairs
//   (nk,nv,nn) — interned namespace-label pairs of the pod's namespace
//   out[c]         — 1 iff column c matches (0 for general columns)
//   general_out[c] — 1 iff Python must evaluate column c (gate passed)
// Both outputs must hold ktn_num_cols entries.
void ktn_match_row(void* h, int32_t pod_ns, int32_t ns_exists,
                   const int32_t* pk, const int32_t* pv, int32_t np,
                   const int32_t* nk, const int32_t* nv, int32_t nn,
                   uint8_t* out, uint8_t* general_out) {
  Engine* e = static_cast<Engine*>(h);
  const int32_t T = static_cast<int32_t>(e->cols.size());
  std::memset(out, 0, T);
  std::memset(general_out, 0, T);
  if (static_cast<int32_t>(e->stamp.size()) < T) e->stamp.resize(T, 0);
  const int64_t epoch = ++e->epoch;

  auto eval = [&](int32_t c) {
    if (e->stamp[c] == epoch) return;  // already evaluated this call
    e->stamp[c] = epoch;
    const Col& col = e->cols[c];
    if (!col.valid) return;
    if (!e->cluster) {
      if (col.thr_ns != pod_ns) return;
    } else if (!ns_exists) {
      return;
    }
    if (col.general) {
      general_out[c] = 1;
      return;
    }
    for (const Term& t : col.terms) {
      if (!pairs_match(t.pod, pk, pv, np)) continue;
      if (e->cluster && !pairs_match(t.ns, nk, nv, nn)) continue;
      out[c] = 1;
      break;
    }
  };

  // candidates: columns whose bucketing pair the pod actually carries,
  // plus the always list (general columns / no-pod-requirement terms) —
  // a term's first requirement unmatched ⇒ the term cannot match, so
  // non-candidates are provably non-matching
  for (int32_t c : e->always) eval(c);
  for (int32_t i = 0; i < np; ++i) {
    auto it = e->buckets.find(bucket_key(pk[i], pv[i]));
    if (it == e->buckets.end()) continue;
    for (int32_t c : it->second) eval(c);
  }
}

}  // extern "C"
