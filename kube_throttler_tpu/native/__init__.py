"""ctypes bindings for the native selector row-match engine.

The shared library is built from ``ktnative.cpp`` in this package
(``make native``), which ships with the wheel so installed copies
auto-build too.
If it is absent, the loader builds it on first import with ``g++`` — a
single-file, sub-second compile — and falls back to pure Python when no
toolchain is available, so the package never hard-depends on the binary.

Build destination: the package directory when writable (dev checkouts);
otherwise a per-user cache dir (``$XDG_CACHE_HOME/kube-throttler-tpu``,
mode 0700) keyed by the source hash — a read-only site-packages install
builds ONCE per user instead of failing the in-package write and
re-attempting g++ in every process.

Set ``KT_TPU_NO_NATIVE=1`` to force the Python tier (used by parity tests).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
import threading
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_PKG_DIR = Path(__file__).resolve().parent
_SRC = _PKG_DIR / "ktnative.cpp"
_SO = _PKG_DIR / "_ktnative.so"

_lib: Optional[ctypes.CDLL] = None
from ..utils.lockorder import make_lock as _make_lock

_load_lock = _make_lock("native.load")
_load_attempted = False

_i32p = ctypes.POINTER(ctypes.c_int32)


logger = logging.getLogger(__name__)

CXX_FLAGS = ["-O3", "-std=c++17", "-shared", "-fPIC"]


def _user_cache_so() -> Optional[Path]:
    """Per-user build destination for read-only installs, keyed by source
    hash so a package upgrade invalidates stale binaries automatically."""
    import hashlib

    try:
        digest = hashlib.sha256(_SRC.read_bytes()).hexdigest()[:16]
        base = Path(
            os.environ.get("XDG_CACHE_HOME") or (Path.home() / ".cache")
        )
        cache_dir = base / "kube-throttler-tpu"
        cache_dir.mkdir(mode=0o700, parents=True, exist_ok=True)
        return cache_dir / f"_ktnative-{digest}.so"
    except OSError:
        return None


def _build(target: Path) -> bool:
    """Compile to a temp file and atomically rename, so concurrent importers
    never dlopen a partially written library."""
    if not _SRC.exists():
        return False
    tmp = None
    try:
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=str(target.parent))
        os.close(fd)
        subprocess.run(
            ["g++", *CXX_FLAGS, str(_SRC), "-o", tmp],
            check=True,
            capture_output=True,
            timeout=120,
        )
        # in-package builds stay world-readable (shared installs); the
        # per-user cache keeps mkstemp's 0600
        if target.parent == _PKG_DIR:
            os.chmod(tmp, 0o644)
        os.replace(tmp, target)
        return True
    except (OSError, subprocess.SubprocessError) as exc:
        logger.warning(
            "native selector engine build failed (%s); falling back to the "
            "pure-Python row-match tier",
            exc,
        )
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return False


def _declare(lib: ctypes.CDLL) -> None:
    lib.ktn_create.argtypes = [ctypes.c_int32]
    lib.ktn_create.restype = ctypes.c_void_p
    lib.ktn_destroy.argtypes = [ctypes.c_void_p]
    lib.ktn_destroy.restype = None
    lib.ktn_reserve.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.ktn_reserve.restype = None
    lib.ktn_set_col.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        _i32p, _i32p, _i32p, _i32p, _i32p,  # pod side (nested CSR)
        _i32p, _i32p, _i32p, _i32p, _i32p,  # ns side
    ]
    lib.ktn_set_col.restype = None
    lib.ktn_set_col_general.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32]
    lib.ktn_set_col_general.restype = None
    lib.ktn_clear_col.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.ktn_clear_col.restype = None
    lib.ktn_num_cols.argtypes = [ctypes.c_void_p]
    lib.ktn_num_cols.restype = ctypes.c_int32
    # raw-pointer-int args (c_void_p) on the hot row-match: each data_as
    # POINTER conversion costs ~2µs and the call makes six — at 2 kinds ×
    # 100k pod events that marshaling alone was seconds of cold start
    lib.ktn_match_row.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
        ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.ktn_match_row.restype = None
    # single-pod classifier: planes registered once per staging allocation
    # (ktn_cls_create), per-call args are raw pointer ints (c_void_p) so the
    # hot call marshals 8 scalars instead of 22 numpy data_as conversions
    lib.ktn_cls_create.argtypes = [ctypes.c_int32] + [ctypes.c_void_p] * 16
    lib.ktn_cls_create.restype = ctypes.c_void_p
    lib.ktn_cls_destroy.argtypes = [ctypes.c_void_p]
    lib.ktn_cls_destroy.restype = None
    lib.ktn_cls_run.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_void_p,
    ]
    lib.ktn_cls_run.restype = None


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library, or None."""
    global _lib, _load_attempted
    if os.environ.get("KT_TPU_NO_NATIVE") == "1":
        return None
    with _load_lock:
        if _load_attempted:
            return _lib
        _load_attempted = True
        so = _SO
        fresh = so.exists() and not (
            _SRC.exists() and so.stat().st_mtime < _SRC.stat().st_mtime
        )
        if not fresh:
            if os.access(_PKG_DIR, os.W_OK):
                if not _build(_SO):
                    return None
            else:
                # read-only install: build (once) into the per-user cache
                cached = _user_cache_so()
                if cached is None:
                    return None
                if not cached.exists() and not _build(cached):
                    return None
                so = cached
        try:
            lib = ctypes.CDLL(str(so))
            _declare(lib)
            _lib = lib
        except (OSError, AttributeError) as exc:
            # AttributeError: a prebuilt .so predating a symbol added to
            # _declare (archive extraction can set mtimes that defeat the
            # source-mtime freshness check) — degrade to the Python tier
            # like any other load failure instead of crashing the caller
            logger.warning(
                "native selector engine load failed (%s); falling back to the "
                "pure-Python row-match tier",
                exc,
            )
            _lib = None
        return _lib


def available() -> bool:
    return load() is not None


def _as_i32(seq: Sequence[int]) -> np.ndarray:
    return np.asarray(seq, dtype=np.int32)


def _ptr(arr: np.ndarray) -> _i32p:
    return arr.ctypes.data_as(_i32p)


class NativeRowEngine:
    """One engine per SelectorIndex — wraps the C row-match kernel.

    All interning happens in the caller; this class only marshals int32
    arrays across the ctypes boundary.  Thread safety is the caller's
    (SelectorIndex holds its RLock around every call).
    """

    def __init__(self, kind: str):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = ctypes.c_void_p(lib.ktn_create(1 if kind == "clusterthrottle" else 0))
        # ktn_num_cols cached per column-set mutation (set_col can extend):
        # the hot match_row otherwise pays an extra ctypes call per row
        self._n_cols: Optional[int] = None
        # (out, general) uint8 scratch for match_row — see its docstring
        self._match_scratch: Optional[Tuple[np.ndarray, np.ndarray]] = None

    def __del__(self) -> None:  # pragma: no cover - interpreter teardown
        h = getattr(self, "_h", None)
        if h:
            try:
                self._lib.ktn_destroy(h)
            except Exception:
                pass
            self._h = None

    def reserve(self, tcap: int) -> None:
        self._lib.ktn_reserve(self._h, tcap)
        self._n_cols = None

    # operator codes — shared contract with the Op enum in ktnative.cpp
    OP_EQ = 0
    OP_IN = 1
    OP_NOT_IN = 2
    OP_EXISTS = 3
    OP_DOES_NOT_EXIST = 4

    @staticmethod
    def _flatten_side(terms_side) -> Tuple[np.ndarray, ...]:
        """Nested CSR for one selector side: terms_side is a list (per
        term) of requirement lists [(key_id, op, (value_ids...))]."""
        term_off = [0]
        keys: List[int] = []
        ops: List[int] = []
        voff = [0]
        vals: List[int] = []
        for reqs in terms_side:
            for key, op, values in reqs:
                keys.append(key)
                ops.append(op)
                vals.extend(values)
                voff.append(len(vals))
            term_off.append(len(keys))
        return (
            _as_i32(term_off), _as_i32(keys), _as_i32(ops),
            _as_i32(voff), _as_i32(vals),
        )

    def set_col(
        self,
        col: int,
        thr_ns: int,
        terms: Sequence[Tuple[Sequence[Tuple[int, int, Sequence[int]]],
                              Sequence[Tuple[int, int, Sequence[int]]]]],
    ) -> None:
        """terms: [(pod_reqs, ns_reqs)] with reqs as
        (key_id, op, value_ids) — op per the OP_* codes (matchLabels
        entries are OP_EQ with one value)."""
        pod_arrays = self._flatten_side([t[0] for t in terms])
        ns_arrays = self._flatten_side([t[1] for t in terms])
        self._lib.ktn_set_col(
            self._h, col, thr_ns, len(terms),
            *(_ptr(a) for a in pod_arrays),
            *(_ptr(a) for a in ns_arrays),
        )
        self._n_cols = None

    def set_col_general(self, col: int, thr_ns: int) -> None:
        self._lib.ktn_set_col_general(self._h, col, thr_ns)
        self._n_cols = None

    def clear_col(self, col: int) -> None:
        self._lib.ktn_clear_col(self._h, col)
        self._n_cols = None

    def match_row(
        self,
        pod_ns: int,
        ns_exists: bool,
        pod_labels: Dict[int, int],
        ns_labels: Dict[int, int],
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (match, needs_general) as uint8 arrays of length num_cols.

        The returned arrays are per-engine SCRATCH, valid only until the
        next match_row call — the caller contract (SelectorIndex holds its
        RLock around every call AND copies what it keeps —
        engine/index.py _match_row_arbitrary) makes reuse safe and saves
        two allocations on the hot pod-event path. Pointer args pass as
        raw ints (see _declare)."""
        n_cols = self._n_cols
        if n_cols is None:
            n_cols = self._n_cols = self._lib.ktn_num_cols(self._h)
        sc = self._match_scratch
        if sc is None or sc[0].shape[0] < n_cols:
            # np.empty: ktn_match_row memsets both buffers itself
            sc = (np.empty(n_cols, dtype=np.uint8), np.empty(n_cols, dtype=np.uint8))
            self._match_scratch = sc
        out, general = sc[0][:n_cols], sc[1][:n_cols]
        pk = np.fromiter(pod_labels.keys(), dtype=np.int32, count=len(pod_labels))
        pv = np.fromiter(pod_labels.values(), dtype=np.int32, count=len(pod_labels))
        nk = np.fromiter(ns_labels.keys(), dtype=np.int32, count=len(ns_labels))
        nv = np.fromiter(ns_labels.values(), dtype=np.int32, count=len(ns_labels))
        self._lib.ktn_match_row(
            self._h, pod_ns, 1 if ns_exists else 0,
            pk.ctypes.data, pv.ctypes.data, len(pk),
            nk.ctypes.data, nv.ctypes.data, len(nk),
            out.ctypes.data, general.ctypes.data,
        )
        return out, general
