"""Manifest (de)serialization — YAML/JSON dicts ↔ typed API objects.

Accepts the same manifest shapes as the reference CRDs (see
/root/reference/example/*.yaml and deploy/crd.yaml): ``spec.throttlerName``,
``spec.selector.selectorTerms[].podSelector/namespaceSelector`` (matchLabels +
matchExpressions), ``spec.threshold.resourceCounts.pod`` /
``.resourceRequests``, and ``spec.temporaryThresholdOverrides[].begin/end/
threshold``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional

from ..quantity import parse_quantity
from .pod import Container, Pod, PodSpec, PodStatus
from .types import (
    ClusterThrottle,
    ClusterThrottleSelector,
    ClusterThrottleSelectorTerm,
    ClusterThrottleSpec,
    LabelSelector,
    LabelSelectorRequirement,
    ResourceAmount,
    TemporaryThresholdOverride,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)


def resource_amount_from_dict(d: Optional[Mapping[str, Any]]) -> ResourceAmount:
    if not d:
        return ResourceAmount()
    counts = d.get("resourceCounts")
    requests = d.get("resourceRequests")
    # presence of the resourceCounts *object* is what matters: Go unmarshals
    # `resourceCounts: {}` to &ResourceCounts{Pod: 0} — an active zero
    # pod-count threshold that blocks every pod, not an absent dimension
    return ResourceAmount(
        resource_counts=int(counts.get("pod", 0)) if counts is not None else None,
        resource_requests=(
            {str(k): parse_quantity(v) for k, v in requests.items()}
            if requests is not None
            else None
        ),
    )


def label_selector_from_dict(d: Optional[Mapping[str, Any]]) -> LabelSelector:
    if not d:
        return LabelSelector()
    exprs = tuple(
        LabelSelectorRequirement(
            key=str(e["key"]),
            operator=str(e.get("operator", "")),
            values=tuple(str(v) for v in e.get("values", []) or []),
        )
        for e in d.get("matchExpressions", []) or []
    )
    return LabelSelector(
        match_labels={str(k): str(v) for k, v in (d.get("matchLabels") or {}).items()},
        match_expressions=exprs,
    )


def _overrides_from_list(items: Optional[List[Mapping[str, Any]]]):
    return tuple(
        TemporaryThresholdOverride(
            begin=str(o.get("begin", "") or ""),
            end=str(o.get("end", "") or ""),
            threshold=resource_amount_from_dict(o.get("threshold")),
        )
        for o in (items or [])
    )


def throttle_from_dict(d: Mapping[str, Any]) -> Throttle:
    meta = d.get("metadata", {})
    spec = d.get("spec", {})
    selector = spec.get("selector", {}) or {}
    terms = tuple(
        ThrottleSelectorTerm(pod_selector=label_selector_from_dict(t.get("podSelector")))
        for t in (selector.get("selectorTerms") or selector.get("selecterTerms") or [])
    )
    return Throttle(
        name=str(meta.get("name", "")),
        namespace=str(meta.get("namespace", "default") or "default"),
        uid=str(meta.get("uid", "")),
        spec=ThrottleSpec(
            throttler_name=str(spec.get("throttlerName", "")),
            threshold=resource_amount_from_dict(spec.get("threshold")),
            temporary_threshold_overrides=_overrides_from_list(
                spec.get("temporaryThresholdOverrides")
            ),
            selector=ThrottleSelector(selector_terms=terms),
        ),
    )


def cluster_throttle_from_dict(d: Mapping[str, Any]) -> ClusterThrottle:
    meta = d.get("metadata", {})
    spec = d.get("spec", {})
    selector = spec.get("selector", {}) or {}
    terms = tuple(
        ClusterThrottleSelectorTerm(
            pod_selector=label_selector_from_dict(t.get("podSelector")),
            namespace_selector=label_selector_from_dict(t.get("namespaceSelector")),
        )
        for t in (selector.get("selectorTerms") or selector.get("selecterTerms") or [])
    )
    return ClusterThrottle(
        name=str(meta.get("name", "")),
        uid=str(meta.get("uid", "")),
        spec=ClusterThrottleSpec(
            throttler_name=str(spec.get("throttlerName", "")),
            threshold=resource_amount_from_dict(spec.get("threshold")),
            temporary_threshold_overrides=_overrides_from_list(
                spec.get("temporaryThresholdOverrides")
            ),
            selector=ClusterThrottleSelector(selector_terms=terms),
        ),
    )


def pod_from_dict(d: Mapping[str, Any]) -> Pod:
    meta = d.get("metadata", {})
    spec = d.get("spec", {})
    status = d.get("status", {})

    def containers(key: str) -> List[Container]:
        out = []
        for c in spec.get(key, []) or []:
            reqs = (c.get("resources", {}) or {}).get("requests", {}) or {}
            out.append(Container.of(reqs, name=str(c.get("name", ""))))
        return out

    overhead = spec.get("overhead")
    return Pod(
        name=str(meta.get("name", "")),
        namespace=str(meta.get("namespace", "default") or "default"),
        labels={str(k): str(v) for k, v in (meta.get("labels") or {}).items()},
        spec=PodSpec(
            scheduler_name=str(spec.get("schedulerName", "")),
            node_name=str(spec.get("nodeName", "") or ""),
            containers=containers("containers"),
            init_containers=containers("initContainers"),
            overhead={k: parse_quantity(v) for k, v in overhead.items()}
            if overhead
            else None,
        ),
        status=PodStatus(phase=str(status.get("phase", "Pending") or "Pending")),
    )


def object_from_dict(d: Mapping[str, Any]):
    kind = d.get("kind", "")
    if kind == "Throttle":
        return throttle_from_dict(d)
    if kind == "ClusterThrottle":
        return cluster_throttle_from_dict(d)
    if kind == "Pod":
        return pod_from_dict(d)
    raise ValueError(f"unsupported kind: {kind!r}")
