"""Manifest (de)serialization — YAML/JSON dicts ↔ typed API objects.

Accepts the same manifest shapes as the reference CRDs (see
/root/reference/example/*.yaml and deploy/crd.yaml): ``spec.throttlerName``,
``spec.selector.selectorTerms[].podSelector/namespaceSelector`` (matchLabels +
matchExpressions), ``spec.threshold.resourceCounts.pod`` /
``.resourceRequests``, and ``spec.temporaryThresholdOverrides[].begin/end/
threshold``.
"""

from __future__ import annotations

from datetime import date, datetime, timezone
from typing import Any, Dict, List, Mapping, Optional

from ..quantity import format_quantity, parse_quantity
from .pod import Container, Namespace, Pod, PodSpec, PodStatus
from .types import (
    AccelClassThreshold,
    CalculatedThreshold,
    ClusterThrottle,
    ClusterThrottleSelector,
    ClusterThrottleSelectorTerm,
    ClusterThrottleSpec,
    IsResourceAmountThrottled,
    LabelSelector,
    LabelSelectorRequirement,
    ResourceAmount,
    TemporaryThresholdOverride,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
    ThrottleStatus,
    parse_rfc3339,
)

API_GROUP = "schedule.k8s.everpeace.github.com"
VERSION = "v1alpha1"
API_VERSION = f"{API_GROUP}/{VERSION}"


def resource_amount_from_dict(d: Optional[Mapping[str, Any]]) -> ResourceAmount:
    if not d:
        return ResourceAmount()
    counts = d.get("resourceCounts")
    requests = d.get("resourceRequests")
    # presence of the resourceCounts *object* is what matters: Go unmarshals
    # `resourceCounts: {}` to &ResourceCounts{Pod: 0} — an active zero
    # pod-count threshold that blocks every pod, not an absent dimension
    return ResourceAmount(
        resource_counts=int(counts.get("pod", 0)) if counts is not None else None,
        resource_requests=(
            {str(k): parse_quantity(v) for k, v in requests.items()}
            if requests is not None
            else None
        ),
    )


def label_selector_from_dict(d: Optional[Mapping[str, Any]]) -> LabelSelector:
    if not d:
        return LabelSelector()
    exprs = tuple(
        LabelSelectorRequirement(
            key=str(e["key"]),
            operator=str(e.get("operator", "")),
            values=tuple(str(v) for v in e.get("values", []) or []),
        )
        for e in d.get("matchExpressions", []) or []
    )
    return LabelSelector(
        match_labels={str(k): str(v) for k, v in (d.get("matchLabels") or {}).items()},
        match_expressions=exprs,
    )


def _boundary_str(v: Any) -> str:
    # YAML auto-parses unquoted RFC3339 timestamps into datetime objects
    # (and date-only values into datetime.date); str() would yield
    # "2024-01-01 00:00:00+09:00" (space, not RFC3339), so format explicitly.
    if isinstance(v, datetime):
        if v.tzinfo is None:
            v = v.replace(tzinfo=timezone.utc)
        return v.isoformat().replace("+00:00", "Z")
    if isinstance(v, date):
        return v.isoformat()
    return str(v or "")


def _overrides_from_list(items: Optional[List[Mapping[str, Any]]]):
    return tuple(
        TemporaryThresholdOverride(
            begin=_boundary_str(o.get("begin", "")),
            end=_boundary_str(o.get("end", "")),
            threshold=resource_amount_from_dict(o.get("threshold")),
        )
        for o in (items or [])
    )


def _accel_thresholds_from_list(items: Optional[List[Mapping[str, Any]]]):
    return tuple(
        AccelClassThreshold(
            accel_class=str(e.get("accelClass", "")),
            threshold=resource_amount_from_dict(e.get("threshold")),
        )
        for e in (items or [])
    )


def _accel_thresholds_to_list(entries) -> List[Dict[str, Any]]:
    return [
        {"accelClass": e.accel_class, "threshold": e.threshold.to_dict()}
        for e in entries
    ]


def _throttled_flags_from_dict(d: Optional[Mapping[str, Any]]) -> IsResourceAmountThrottled:
    if not d:
        return IsResourceAmountThrottled()
    counts = d.get("resourceCounts")
    requests = d.get("resourceRequests")
    return IsResourceAmountThrottled(
        resource_counts_pod=bool(counts.get("pod", False)) if counts is not None else False,
        resource_requests=(
            {str(k): bool(v) for k, v in requests.items()} if requests is not None else None
        ),
    )


def status_from_dict(d: Optional[Mapping[str, Any]]) -> ThrottleStatus:
    """Parse the status subresource (throttle_types.go:113-117 shape)."""
    if not d:
        return ThrottleStatus()
    ct = d.get("calculatedThreshold") or {}
    calculated_at = ct.get("calculatedAt")
    return ThrottleStatus(
        calculated_threshold=CalculatedThreshold(
            threshold=resource_amount_from_dict(ct.get("threshold")),
            calculated_at=parse_rfc3339(calculated_at) if calculated_at else None,
            messages=tuple(str(m) for m in ct.get("messages", []) or []),
        ),
        throttled=_throttled_flags_from_dict(d.get("throttled")),
        used=resource_amount_from_dict(d.get("used")),
    )


def throttle_from_dict(d: Mapping[str, Any]) -> Throttle:
    meta = d.get("metadata", {})
    spec = d.get("spec", {})
    selector = spec.get("selector", {}) or {}
    terms = tuple(
        ThrottleSelectorTerm(pod_selector=label_selector_from_dict(t.get("podSelector")))
        for t in (selector.get("selectorTerms") or selector.get("selecterTerms") or [])
    )
    return Throttle(
        name=str(meta.get("name", "")),
        namespace=str(meta.get("namespace", "default") or "default"),
        uid=str(meta.get("uid", "")),
        spec=ThrottleSpec(
            throttler_name=str(spec.get("throttlerName", "")),
            threshold=resource_amount_from_dict(spec.get("threshold")),
            temporary_threshold_overrides=_overrides_from_list(
                spec.get("temporaryThresholdOverrides")
            ),
            accel_class_thresholds=_accel_thresholds_from_list(
                spec.get("accelClassThresholds")
            ),
            selector=ThrottleSelector(selector_terms=terms),
        ),
        status=status_from_dict(d.get("status")),
    )


def cluster_throttle_from_dict(d: Mapping[str, Any]) -> ClusterThrottle:
    meta = d.get("metadata", {})
    spec = d.get("spec", {})
    selector = spec.get("selector", {}) or {}
    terms = tuple(
        ClusterThrottleSelectorTerm(
            pod_selector=label_selector_from_dict(t.get("podSelector")),
            namespace_selector=label_selector_from_dict(t.get("namespaceSelector")),
        )
        for t in (selector.get("selectorTerms") or selector.get("selecterTerms") or [])
    )
    return ClusterThrottle(
        name=str(meta.get("name", "")),
        uid=str(meta.get("uid", "")),
        spec=ClusterThrottleSpec(
            throttler_name=str(spec.get("throttlerName", "")),
            threshold=resource_amount_from_dict(spec.get("threshold")),
            temporary_threshold_overrides=_overrides_from_list(
                spec.get("temporaryThresholdOverrides")
            ),
            accel_class_thresholds=_accel_thresholds_from_list(
                spec.get("accelClassThresholds")
            ),
            selector=ClusterThrottleSelector(selector_terms=terms),
        ),
        status=status_from_dict(d.get("status")),
    )


def pod_from_dict(d: Mapping[str, Any]) -> Pod:
    meta = d.get("metadata", {})
    spec = d.get("spec", {})
    status = d.get("status", {})

    def containers(key: str) -> List[Container]:
        out = []
        for c in spec.get(key, []) or []:
            reqs = (c.get("resources", {}) or {}).get("requests", {}) or {}
            out.append(Container.of(reqs, name=str(c.get("name", ""))))
        return out

    overhead = spec.get("overhead")
    uid_kwargs = {"uid": str(meta["uid"])} if meta.get("uid") else {}
    return Pod(
        name=str(meta.get("name", "")),
        namespace=str(meta.get("namespace", "default") or "default"),
        labels={str(k): str(v) for k, v in (meta.get("labels") or {}).items()},
        annotations={
            str(k): str(v) for k, v in (meta.get("annotations") or {}).items()
        },
        **uid_kwargs,
        spec=PodSpec(
            scheduler_name=str(spec.get("schedulerName", "")),
            node_name=str(spec.get("nodeName", "") or ""),
            containers=containers("containers"),
            init_containers=containers("initContainers"),
            overhead={k: parse_quantity(v) for k, v in overhead.items()}
            if overhead
            else None,
        ),
        status=PodStatus(phase=str(status.get("phase", "Pending") or "Pending")),
    )


def object_from_dict(d: Mapping[str, Any]):
    kind = d.get("kind", "")
    if kind == "Throttle":
        return throttle_from_dict(d)
    if kind == "ClusterThrottle":
        return cluster_throttle_from_dict(d)
    if kind == "Pod":
        return pod_from_dict(d)
    if kind == "Namespace":
        return namespace_from_dict(d)
    raise ValueError(f"unsupported kind: {kind!r}")


def namespace_from_dict(d: Mapping[str, Any]) -> Namespace:
    meta = d.get("metadata", {})
    kwargs = {"uid": str(meta["uid"])} if meta.get("uid") else {}
    return Namespace(
        name=str(meta.get("name", "")),
        labels={str(k): str(v) for k, v in (meta.get("labels") or {}).items()},
        **kwargs,
    )


def normalize_manifest(d: Any) -> Any:
    """Recursively rewrite the reference API's typo spelling ``selecterTerms``
    (throttle_selector.go:27 — an accepted input everywhere) to the canonical
    ``selectorTerms``. Needed before a JSON merge patch: merging a typo-keyed
    patch into a canonically-keyed document would otherwise leave BOTH keys,
    and the reader's precedence would pick the stale canonical one.

    Also renders YAML's auto-parsed timestamps (datetime and date-only)
    back to RFC3339 strings — the wire format is JSON, where they are
    strings (kubectl does the same YAML→JSON conversion before sending)."""
    if isinstance(d, (datetime, date)):
        return _boundary_str(d)
    if isinstance(d, dict):
        out = {}
        for k, v in d.items():
            key = "selectorTerms" if k == "selecterTerms" else k
            out[key] = normalize_manifest(v)
        return out
    if isinstance(d, list):
        return [normalize_manifest(v) for v in d]
    return d


# ---------------------------------------------------------------------------
# typed objects → manifest dicts (the serializer half the generated clients'
# Patch verb needs: round-trippable through *_from_dict above)
# ---------------------------------------------------------------------------


def label_selector_to_dict(sel: LabelSelector) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    if sel.match_labels:
        out["matchLabels"] = dict(sorted(sel.match_labels.items()))
    if sel.match_expressions:
        out["matchExpressions"] = [
            {"key": e.key, "operator": e.operator, **({"values": list(e.values)} if e.values else {})}
            for e in sel.match_expressions
        ]
    return out


def _overrides_to_list(overrides) -> List[Dict[str, Any]]:
    return [
        {
            **({"begin": o.begin} if o.begin else {}),
            **({"end": o.end} if o.end else {}),
            "threshold": o.threshold.to_dict(),
        }
        for o in overrides
    ]


def status_to_dict(status: ThrottleStatus) -> Dict[str, Any]:
    ct = status.calculated_threshold
    return {
        "used": status.used.to_dict(),
        "throttled": status.throttled.to_dict(),
        "calculatedThreshold": {
            "threshold": ct.threshold.to_dict(),
            "calculatedAt": (
                # full precision (isoformat keeps microseconds; parse_rfc3339
                # accepts them) so to_dict/from_dict round-trips clock-stamped
                # statuses exactly
                ct.calculated_at.astimezone(timezone.utc).isoformat().replace("+00:00", "Z")
                if ct.calculated_at
                else None
            ),
            "messages": list(ct.messages),
        },
    }


def throttle_to_dict(thr: Throttle) -> Dict[str, Any]:
    return {
        "apiVersion": API_VERSION,
        "kind": "Throttle",
        "metadata": {
            "name": thr.name,
            "namespace": thr.namespace,
            **({"uid": thr.uid} if thr.uid else {}),
        },
        "spec": {
            **({"throttlerName": thr.spec.throttler_name} if thr.spec.throttler_name else {}),
            "threshold": thr.spec.threshold.to_dict(),
            **(
                {
                    "temporaryThresholdOverrides": _overrides_to_list(
                        thr.spec.temporary_threshold_overrides
                    )
                }
                if thr.spec.temporary_threshold_overrides
                else {}
            ),
            **(
                {
                    "accelClassThresholds": _accel_thresholds_to_list(
                        thr.spec.accel_class_thresholds
                    )
                }
                if thr.spec.accel_class_thresholds
                else {}
            ),
            "selector": {
                "selectorTerms": [
                    {"podSelector": label_selector_to_dict(t.pod_selector)}
                    for t in thr.spec.selector.selector_terms
                ]
            },
        },
        "status": status_to_dict(thr.status),
    }


def cluster_throttle_to_dict(thr: ClusterThrottle) -> Dict[str, Any]:
    return {
        "apiVersion": API_VERSION,
        "kind": "ClusterThrottle",
        "metadata": {"name": thr.name, **({"uid": thr.uid} if thr.uid else {})},
        "spec": {
            **({"throttlerName": thr.spec.throttler_name} if thr.spec.throttler_name else {}),
            "threshold": thr.spec.threshold.to_dict(),
            **(
                {
                    "temporaryThresholdOverrides": _overrides_to_list(
                        thr.spec.temporary_threshold_overrides
                    )
                }
                if thr.spec.temporary_threshold_overrides
                else {}
            ),
            **(
                {
                    "accelClassThresholds": _accel_thresholds_to_list(
                        thr.spec.accel_class_thresholds
                    )
                }
                if thr.spec.accel_class_thresholds
                else {}
            ),
            "selector": {
                "selectorTerms": [
                    {
                        "podSelector": label_selector_to_dict(t.pod_selector),
                        "namespaceSelector": label_selector_to_dict(t.namespace_selector),
                    }
                    for t in thr.spec.selector.selector_terms
                ]
            },
        },
        "status": status_to_dict(thr.status),
    }


def pod_to_dict(pod: Pod) -> Dict[str, Any]:
    def containers(cs: List[Container]) -> List[Dict[str, Any]]:
        return [
            {
                **({"name": c.name} if c.name else {}),
                "resources": {
                    "requests": {k: format_quantity(v) for k, v in sorted(c.requests.items())}
                },
            }
            for c in cs
        ]

    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": pod.name,
            "namespace": pod.namespace,
            **({"uid": pod.uid} if pod.uid else {}),
            **({"labels": dict(sorted(pod.labels.items()))} if pod.labels else {}),
            **(
                {"annotations": dict(sorted(pod.annotations.items()))}
                if pod.annotations
                else {}
            ),
        },
        "spec": {
            **({"schedulerName": pod.spec.scheduler_name} if pod.spec.scheduler_name else {}),
            **({"nodeName": pod.spec.node_name} if pod.spec.node_name else {}),
            "containers": containers(pod.spec.containers),
            **(
                {"initContainers": containers(pod.spec.init_containers)}
                if pod.spec.init_containers
                else {}
            ),
            **(
                {"overhead": {k: format_quantity(v) for k, v in sorted(pod.spec.overhead.items())}}
                if pod.spec.overhead
                else {}
            ),
        },
        "status": {"phase": pod.status.phase},
    }


def namespace_to_dict(ns: Namespace) -> Dict[str, Any]:
    return {
        "apiVersion": "v1",
        "kind": "Namespace",
        "metadata": {
            "name": ns.name,
            **({"uid": ns.uid} if ns.uid else {}),
            **({"labels": dict(sorted(ns.labels.items()))} if ns.labels else {}),
        },
    }


def object_to_dict(obj) -> Dict[str, Any]:
    if isinstance(obj, Throttle):
        return throttle_to_dict(obj)
    if isinstance(obj, ClusterThrottle):
        return cluster_throttle_to_dict(obj)
    if isinstance(obj, Pod):
        return pod_to_dict(obj)
    if isinstance(obj, Namespace):
        return namespace_to_dict(obj)
    raise ValueError(f"unsupported object: {type(obj).__name__}")
