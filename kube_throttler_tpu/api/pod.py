"""Minimal Pod / Namespace model.

The reference consumes upstream ``corev1.Pod``; the new framework is
standalone, so this module defines exactly the slice of the pod object the
throttler reads:

- ``metadata``: namespace/name/uid/labels (selector matching, ledger keys);
- ``spec``: schedulerName + nodeName (count-in predicate), container /
  init-container requests + overhead (effective request);
- ``status.phase`` (terminated predicate).

Predicates mirror the reference's pkg/controllers/pod_util.go:
``is_scheduled`` = NodeName != "" (pod_util.go:300-302 per SURVEY);
``is_not_finished`` = phase ∉ {Succeeded, Failed}.

**Gang / heterogeneity annotations.** The gang-admission subsystem
(engine/gang.py, docs/gang_admission.md) reads its PodGroup contract from
pod annotations — the same place kube-batch/volcano-style gang schedulers
put theirs:

- ``kube-throttler.github.io/pod-group``: the group name. All ranks of one
  tightly-coupled job carry the same name; the group key is
  ``namespace/name`` (gangs never span namespaces).
- ``kube-throttler.github.io/pod-group-size``: the expected member count
  (min-available). Admission is all-or-nothing across exactly this many
  ranks; a malformed or non-positive size disables gang handling for the
  pod (it degrades to per-pod admission — a typo must not wedge a pod
  forever behind a group that can never form).
- ``kube-throttler.github.io/accel-class``: the accelerator class the pod
  runs on (e.g. ``tpu-v5e``); selects the per-class effective threshold a
  throttle may declare (api/types.py ``AccelClassThreshold``).
- ``kube-throttler.github.io/priority``: integer admission priority
  (higher admits first). When capacity opens, parked candidates re-enter
  the scheduler's queue in (priority desc, age) order — the
  preemption-ordered admission lane. Malformed values read as 0.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from functools import cached_property
from typing import Dict, List, Mapping, Optional, Union

from ..quantity import parse_quantity
from ..resourcelist import ResourceList

GROUP_NAME_ANNOTATION = "kube-throttler.github.io/pod-group"
GROUP_SIZE_ANNOTATION = "kube-throttler.github.io/pod-group-size"
ACCEL_CLASS_ANNOTATION = "kube-throttler.github.io/accel-class"
PRIORITY_ANNOTATION = "kube-throttler.github.io/priority"

_uid_counter = itertools.count(1)


def _gen_uid() -> str:
    return f"uid-{next(_uid_counter)}"


@dataclass
class Container:
    requests: ResourceList = field(default_factory=dict)
    name: str = ""

    @staticmethod
    def of(requests: Mapping[str, Union[str, int, float]], name: str = "") -> "Container":
        return Container(
            requests={k: parse_quantity(v) for k, v in requests.items()}, name=name
        )


@dataclass
class PodSpec:
    scheduler_name: str = ""
    node_name: str = ""
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    overhead: Optional[ResourceList] = None


@dataclass
class PodStatus:
    phase: str = "Pending"  # Pending | Running | Succeeded | Failed | Unknown


@dataclass
class Pod:
    name: str
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    uid: str = field(default_factory=_gen_uid)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    @cached_property
    def key(self) -> str:
        """namespace/name — the NamespacedName string form used everywhere.
        Cached: identity fields never mutate by contract (updates go
        through dataclasses.replace, which builds a fresh instance)."""
        return f"{self.namespace}/{self.name}"

    def is_scheduled(self) -> bool:
        return self.spec.node_name != ""

    def is_not_finished(self) -> bool:
        return self.status.phase not in ("Succeeded", "Failed")


@dataclass(frozen=True)
class PodGroup:
    """The gang contract one pod declares: which group it belongs to and
    how many ranks the group needs before any of them may admit."""

    key: str  # "namespace/name" — gangs never span namespaces
    name: str
    size: int


def pod_group_of(pod: "Pod") -> Optional[PodGroup]:
    """Parse the PodGroup annotations, or None when the pod is not gang-
    scheduled. A malformed or non-positive size also yields None: a typo
    must degrade to per-pod admission, never wedge the pod behind a group
    that can never form."""
    name = pod.annotations.get(GROUP_NAME_ANNOTATION, "")
    if not name:
        return None
    raw = pod.annotations.get(GROUP_SIZE_ANNOTATION, "")
    try:
        size = int(raw)
    except (TypeError, ValueError):
        return None
    if size <= 0:
        return None
    return PodGroup(key=f"{pod.namespace}/{name}", name=name, size=size)


def accel_class_of(pod: "Pod") -> Optional[str]:
    """The pod's accelerator class annotation, or None. Falls back to the
    same-named label (some fleets stamp node-selector-style labels)."""
    return (
        pod.annotations.get(ACCEL_CLASS_ANNOTATION)
        or pod.labels.get(ACCEL_CLASS_ANNOTATION)
        or None
    )


def priority_of(pod: "Pod") -> int:
    """Integer admission priority (higher first); malformed values read
    as 0 so a typo cannot starve or catapult a pod."""
    raw = pod.annotations.get(PRIORITY_ANNOTATION, "")
    if not raw:
        return 0
    try:
        return int(raw)
    except (TypeError, ValueError):
        return 0


@dataclass
class Namespace:
    name: str
    labels: Dict[str, str] = field(default_factory=dict)
    uid: str = field(default_factory=_gen_uid)


def make_pod(
    name: str,
    namespace: str = "default",
    labels: Optional[Dict[str, str]] = None,
    requests: Optional[Mapping[str, Union[str, int, float]]] = None,
    init_requests: Optional[List[Mapping[str, Union[str, int, float]]]] = None,
    overhead: Optional[Mapping[str, Union[str, int, float]]] = None,
    scheduler_name: str = "my-scheduler",
    node_name: str = "",
    phase: str = "Pending",
    annotations: Optional[Dict[str, str]] = None,
    group: Optional[str] = None,
    group_size: Optional[int] = None,
    accel_class: Optional[str] = None,
    priority: Optional[int] = None,
) -> Pod:
    """Test/bench convenience builder (single app container). ``group`` /
    ``group_size`` / ``accel_class`` / ``priority`` are sugar for the gang
    and heterogeneity annotations."""
    containers = [Container.of(requests or {})]
    init_containers = [Container.of(r) for r in (init_requests or [])]
    ann = dict(annotations or {})
    if group is not None:
        ann[GROUP_NAME_ANNOTATION] = group
    if group_size is not None:
        ann[GROUP_SIZE_ANNOTATION] = str(group_size)
    if accel_class is not None:
        ann[ACCEL_CLASS_ANNOTATION] = accel_class
    if priority is not None:
        ann[PRIORITY_ANNOTATION] = str(priority)
    return Pod(
        name=name,
        namespace=namespace,
        labels=dict(labels or {}),
        annotations=ann,
        spec=PodSpec(
            scheduler_name=scheduler_name,
            node_name=node_name,
            containers=containers,
            init_containers=init_containers,
            overhead={k: parse_quantity(v) for k, v in overhead.items()}
            if overhead
            else None,
        ),
        status=PodStatus(phase=phase),
    )
