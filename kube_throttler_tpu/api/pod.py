"""Minimal Pod / Namespace model.

The reference consumes upstream ``corev1.Pod``; the new framework is
standalone, so this module defines exactly the slice of the pod object the
throttler reads:

- ``metadata``: namespace/name/uid/labels (selector matching, ledger keys);
- ``spec``: schedulerName + nodeName (count-in predicate), container /
  init-container requests + overhead (effective request);
- ``status.phase`` (terminated predicate).

Predicates mirror the reference's pkg/controllers/pod_util.go:
``is_scheduled`` = NodeName != "" (pod_util.go:300-302 per SURVEY);
``is_not_finished`` = phase ∉ {Succeeded, Failed}.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from functools import cached_property
from typing import Dict, List, Mapping, Optional, Union

from ..quantity import parse_quantity
from ..resourcelist import ResourceList

_uid_counter = itertools.count(1)


def _gen_uid() -> str:
    return f"uid-{next(_uid_counter)}"


@dataclass
class Container:
    requests: ResourceList = field(default_factory=dict)
    name: str = ""

    @staticmethod
    def of(requests: Mapping[str, Union[str, int, float]], name: str = "") -> "Container":
        return Container(
            requests={k: parse_quantity(v) for k, v in requests.items()}, name=name
        )


@dataclass
class PodSpec:
    scheduler_name: str = ""
    node_name: str = ""
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    overhead: Optional[ResourceList] = None


@dataclass
class PodStatus:
    phase: str = "Pending"  # Pending | Running | Succeeded | Failed | Unknown


@dataclass
class Pod:
    name: str
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    uid: str = field(default_factory=_gen_uid)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    @cached_property
    def key(self) -> str:
        """namespace/name — the NamespacedName string form used everywhere.
        Cached: identity fields never mutate by contract (updates go
        through dataclasses.replace, which builds a fresh instance)."""
        return f"{self.namespace}/{self.name}"

    def is_scheduled(self) -> bool:
        return self.spec.node_name != ""

    def is_not_finished(self) -> bool:
        return self.status.phase not in ("Succeeded", "Failed")


@dataclass
class Namespace:
    name: str
    labels: Dict[str, str] = field(default_factory=dict)
    uid: str = field(default_factory=_gen_uid)


def make_pod(
    name: str,
    namespace: str = "default",
    labels: Optional[Dict[str, str]] = None,
    requests: Optional[Mapping[str, Union[str, int, float]]] = None,
    init_requests: Optional[List[Mapping[str, Union[str, int, float]]]] = None,
    overhead: Optional[Mapping[str, Union[str, int, float]]] = None,
    scheduler_name: str = "my-scheduler",
    node_name: str = "",
    phase: str = "Pending",
) -> Pod:
    """Test/bench convenience builder (single app container)."""
    containers = [Container.of(requests or {})]
    init_containers = [Container.of(r) for r in (init_requests or [])]
    return Pod(
        name=name,
        namespace=namespace,
        labels=dict(labels or {}),
        spec=PodSpec(
            scheduler_name=scheduler_name,
            node_name=node_name,
            containers=containers,
            init_containers=init_containers,
            overhead={k: parse_quantity(v) for k, v in overhead.items()}
            if overhead
            else None,
        ),
        status=PodStatus(phase=phase),
    )
