"""Throttle / ClusterThrottle API types and the pure decision core (oracle).

Mirrors reference pkg/apis/schedule/v1alpha1/:

- ``ResourceAmount`` + ``is_throttled``      — resource_amount.go:28-159
- ``IsResourceAmountThrottled.is_throttled_for`` — resource_amount.go:46-65
- ``TemporaryThresholdOverride``             — temporary_threshold_override.go:26-70
- ``calculate_threshold`` (first-wins merge) — throttle_types.go:65-106
- ``next_override_happens_in``               — throttle_types.go:37-63
- 4-state ``check_throttled_for``            — throttle_types.go:128-153 and
  clusterthrottle_types.go:30-55 (which differ ONLY in step-3's onEqual:
  Throttle hardcodes True, ClusterThrottle passes the caller's flag)
- selectors (OR of terms; term = AND of label selectors)
                                             — throttle_selector.go:26-54,
                                               clusterthrottle_selector.go:26-87

Deliberate divergences from the reference (SURVEY.md §2.3 quirk decisions):
- ``ResourceAmount.add/sub`` are pure (return new objects) instead of
  mutating shared maps; all reference call sites build fresh accumulators so
  observable behavior is identical.
- The ``terminatedPods = append(nonterminatedPods, ...)`` slice bug
  (throttle_controller.go:241) is NOT reproduced; the controller layer
  handles terminated pods correctly for both kinds.
- Typos that are API surface (``selecterTerms`` JSON field, ``kubeconifg``)
  are accepted on input for manifest compatibility (see serialization).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field, replace
from functools import cached_property
from datetime import datetime, timedelta, timezone
from fractions import Fraction
from typing import Dict, List, Optional, Sequence, Tuple

from .. import resourcelist as rl
from ..quantity import parse_quantity
from .pod import Namespace, Pod

# ---------------------------------------------------------------------------
# ResourceAmount
# ---------------------------------------------------------------------------

ZERO = Fraction(0)


@dataclass(frozen=True)
class ResourceAmount:
    """{resourceCounts: {pod: int}|nil, resourceRequests: ResourceList|nil}.

    ``None`` mirrors Go's nil: a nil counts/requests member means the
    dimension family is *absent*, which is semantically different from zero
    (absent dimensions are never evaluated — resource_amount.go:143,151-155).
    """

    resource_counts: Optional[int] = None  # pod count; None == nil *ResourceCounts
    resource_requests: Optional[Dict[str, Fraction]] = None

    @staticmethod
    def of(
        pod: Optional[int] = None,
        requests: Optional[Dict[str, object]] = None,
    ) -> "ResourceAmount":
        return ResourceAmount(
            resource_counts=pod,
            resource_requests=(
                {k: parse_quantity(v) for k, v in requests.items()}
                if requests is not None
                else None
            ),
        )

    def add(self, b: "ResourceAmount") -> "ResourceAmount":
        """resource_amount.go:91-110 (pure variant)."""
        requests = dict(self.resource_requests or {})
        if self.resource_counts is None:
            counts = b.resource_counts
        elif b.resource_counts is not None:
            counts = self.resource_counts + b.resource_counts
        else:
            counts = self.resource_counts
        rl.add(requests, b.resource_requests or {})
        return ResourceAmount(resource_counts=counts, resource_requests=requests)

    def sub(self, b: "ResourceAmount") -> "ResourceAmount":
        """resource_amount.go:112-125 — pod count clamps at 0, requests may go
        negative (SURVEY.md §2.3 quirk 4, preserved)."""
        requests = dict(self.resource_requests or {})
        counts = self.resource_counts
        if self.resource_counts is not None and b.resource_counts is not None:
            counts = max(0, self.resource_counts - b.resource_counts)
        rl.sub(requests, b.resource_requests or {})
        return ResourceAmount(resource_counts=counts, resource_requests=requests)

    def is_throttled(
        self, used: "ResourceAmount", is_throttled_on_equal: bool
    ) -> "IsResourceAmountThrottled":
        """self is the *threshold* (resource_amount.go:127-159).

        Only dimensions present in the threshold are evaluated; threshold
        dimensions absent from ``used`` evaluate to not-throttled.
        """

        def hit(u: Fraction, t: Fraction) -> bool:
            return u >= t if is_throttled_on_equal else u > t

        counts_throttled = False
        if self.resource_counts is not None and used.resource_counts is not None:
            counts_throttled = hit(used.resource_counts, self.resource_counts)

        requests_throttled: Optional[Dict[str, bool]] = None
        if self.resource_requests is not None:
            for rn, qt in self.resource_requests.items():
                if requests_throttled is None:
                    requests_throttled = {}
                used_reqs = used.resource_requests or {}
                if rn in used_reqs:
                    requests_throttled[rn] = hit(used_reqs[rn], qt)
                else:
                    requests_throttled[rn] = False
            # NOTE: Go only allocates the map inside the loop, so an *empty*
            # threshold request map yields a nil flag map — preserved here.

        return IsResourceAmountThrottled(
            resource_counts_pod=counts_throttled,
            resource_requests=requests_throttled,
        )

    def to_dict(self) -> Dict[str, object]:
        from ..quantity import format_quantity

        out: Dict[str, object] = {}
        if self.resource_counts is not None:
            out["resourceCounts"] = {"pod": self.resource_counts}
        if self.resource_requests is not None:
            out["resourceRequests"] = {
                k: format_quantity(v) for k, v in sorted(self.resource_requests.items())
            }
        return out


@dataclass(frozen=True)
class IsResourceAmountThrottled:
    """Per-dimension throttled flags (resource_amount.go:39-44)."""

    resource_counts_pod: bool = False
    resource_requests: Optional[Dict[str, bool]] = None

    def is_throttled_for(self, pod: Pod) -> bool:
        """resource_amount.go:46-65: the pod-count flag always blocks; a
        request flag blocks only if the pod requests that resource non-zero."""
        if self.resource_counts_pod:
            return True
        pod_amount = resource_amount_of_pod(pod)
        flags = self.resource_requests or {}
        for rn, rq in (pod_amount.resource_requests or {}).items():
            if rq == 0:
                continue
            if flags.get(rn, False):
                return True
        return False

    def to_dict(self) -> Dict[str, object]:
        out: Dict[str, object] = {"resourceCounts": {"pod": self.resource_counts_pod}}
        if self.resource_requests is not None:
            out["resourceRequests"] = dict(sorted(self.resource_requests.items()))
        return out


def resource_amount_of_pod(pod: Pod) -> ResourceAmount:
    """resource_amount.go:71-76."""
    return ResourceAmount(
        resource_counts=1,
        resource_requests=rl.pod_request_resource_list(pod),
    )


# ---------------------------------------------------------------------------
# Temporary threshold overrides
# ---------------------------------------------------------------------------

_RFC3339_RE = re.compile(
    r"^(\d{4})-(\d{2})-(\d{2})[Tt](\d{2}):(\d{2}):(\d{2})(\.\d+)?([Zz]|[+-]\d{2}:\d{2})$"
)


class RFC3339ParseError(ValueError):
    pass


def parse_rfc3339(s: str) -> datetime:
    """Strict RFC3339 (Go's ``time.Parse(time.RFC3339, ...)`` layout)."""
    m = _RFC3339_RE.match(s)
    if m is None:
        raise RFC3339ParseError(
            f'parsing time "{s}" as RFC3339: cannot parse {s!r}'
        )
    year, month, day, hour, minute, sec = (int(m.group(i)) for i in range(1, 7))
    frac = m.group(7)
    # exact decimal digits, not float round-trip (".000249" must be 249 µs)
    micro = int(frac[1:7].ljust(6, "0")) if frac else 0
    off = m.group(8)
    try:
        if off in ("Z", "z"):
            tz = timezone.utc
        else:
            sign = 1 if off[0] == "+" else -1
            tz = timezone(sign * timedelta(hours=int(off[1:3]), minutes=int(off[4:6])))
        return datetime(year, month, day, hour, minute, sec, micro, tzinfo=tz)
    except ValueError as e:
        raise RFC3339ParseError(f'parsing time "{s}": {e}') from e


@dataclass(frozen=True)
class TemporaryThresholdOverride:
    """temporary_threshold_override.go:26-70. begin/end are RFC3339 strings;
    empty string means open-ended (zero time). Active iff
    begin ≤ now ∧ (end == "" ∨ now ≤ end) — both boundaries inclusive."""

    begin: str = ""
    end: str = ""
    threshold: ResourceAmount = field(default_factory=ResourceAmount)

    def begin_time(self) -> Optional[datetime]:
        """None mirrors the zero time. Raises RFC3339ParseError on bad input."""
        if self.begin == "":
            return None
        try:
            return parse_rfc3339(self.begin)
        except RFC3339ParseError as e:
            raise RFC3339ParseError(f"Failed to parse Begin: {e}") from e

    def end_time(self) -> Optional[datetime]:
        if self.end == "":
            return None
        try:
            return parse_rfc3339(self.end)
        except RFC3339ParseError as e:
            raise RFC3339ParseError(f"Failed to parse End: {e}") from e

    def is_active(self, now: datetime) -> bool:
        """temporary_threshold_override.go:57-70; raises on parse error."""
        begin_t = self.begin_time()
        end_t = self.end_time()
        begin_ok = begin_t is None or begin_t <= now
        end_ok = end_t is None or now <= end_t
        return begin_ok and end_ok


@dataclass(frozen=True)
class CalculatedThreshold:
    """calculated_threshold.go:24-30."""

    threshold: ResourceAmount = field(default_factory=ResourceAmount)
    calculated_at: Optional[datetime] = None  # None mirrors the zero time
    messages: Tuple[str, ...] = ()


@dataclass(frozen=True)
class AccelClassThreshold:
    """Per-accelerator-class effective threshold (heterogeneity-aware
    admission, docs/gang_admission.md).

    A mixed fleet's throttle capacity depends on which accelerator class a
    pod lands on: the same ``cpu: 10`` budget may admit 40 v5e ranks but
    only 8 v5p ranks. A spec may declare a list of these; for a pod whose
    ``accel-class`` annotation equals ``accel_class``, the FIRST matching
    entry's threshold REPLACES the throttle's effective (override-resolved)
    threshold entirely — the same first-wins / whole-replacement semantics
    as temporaryThresholdOverrides, so the two mechanisms compose without a
    per-dimension merge ambiguity. Pods without a class (or with a class no
    entry names) use the base effective threshold.

    The persisted ``status.throttled`` flags stay class-agnostic (they are
    derived from the base threshold at reconcile); class resolution applies
    to the live admission inequality (steps 1/3/4), not to step 2."""

    accel_class: str = ""
    threshold: ResourceAmount = field(default_factory=ResourceAmount)


# ---------------------------------------------------------------------------
# Selectors
# ---------------------------------------------------------------------------


class SelectorError(ValueError):
    """Invalid label selector (mirrors LabelSelectorAsSelector errors)."""


_VALID_OPS = ("In", "NotIn", "Exists", "DoesNotExist")


@dataclass(frozen=True)
class LabelSelectorRequirement:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist
    values: Tuple[str, ...] = ()


@dataclass(frozen=True)
class LabelSelector:
    """metav1.LabelSelector: AND of matchLabels + matchExpressions.

    An empty (but present) selector matches everything — the reference's
    selector *terms* hold LabelSelector by value, so a term with no
    constraints matches every pod (SURVEY §2: "empty term matches
    everything").
    """

    match_labels: Dict[str, str] = field(default_factory=dict)
    match_expressions: Tuple[LabelSelectorRequirement, ...] = ()

    def validate(self) -> None:
        """Mirror LabelSelectorAsSelector: the whole selector is validated
        before any label is compared, so an invalid selector errors even when
        matchLabels alone would already fail the match."""
        for req in self.match_expressions:
            if req.operator not in _VALID_OPS:
                raise SelectorError(f"{req.operator!r} is not a valid label selector operator")
            if req.operator in ("In", "NotIn") and not req.values:
                raise SelectorError("values must be specified when `operator` is 'In' or 'NotIn'")
            if req.operator in ("Exists", "DoesNotExist") and req.values:
                raise SelectorError("values must not be specified when `operator` is 'Exists' or 'DoesNotExist'")

    def matches(self, labels: Dict[str, str]) -> bool:
        self.validate()
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        for req in self.match_expressions:
            present = req.key in labels
            if req.operator == "In":
                if not present or labels[req.key] not in req.values:
                    return False
            elif req.operator == "NotIn":
                if present and labels[req.key] in req.values:
                    return False
            elif req.operator == "Exists":
                if not present:
                    return False
            else:  # DoesNotExist
                if present:
                    return False
        return True


@dataclass(frozen=True)
class ThrottleSelectorTerm:
    """throttle_selector.go:44-54."""

    pod_selector: LabelSelector = field(default_factory=LabelSelector)

    def matches_to_pod(self, pod: Pod) -> bool:
        return self.pod_selector.matches(pod.labels)


@dataclass(frozen=True)
class ThrottleSelector:
    """throttle_selector.go:26-42: OR of terms; no terms → matches nothing."""

    selector_terms: Tuple[ThrottleSelectorTerm, ...] = ()

    def matches_to_pod(self, pod: Pod) -> bool:
        for term in self.selector_terms:
            if term.matches_to_pod(pod):
                return True
        return False


@dataclass(frozen=True)
class ClusterThrottleSelectorTerm:
    """clusterthrottle_selector.go:58-87: namespaceSelector ∧ podSelector.

    A namespace-selector *error* is swallowed as no-match (Go returns
    ``false, nil`` at clusterthrottle_selector.go:63-68 — preserved)."""

    pod_selector: LabelSelector = field(default_factory=LabelSelector)
    namespace_selector: LabelSelector = field(default_factory=LabelSelector)

    def matches_to_namespace(self, ns: Namespace) -> bool:
        try:
            return self.namespace_selector.matches(ns.labels)
        except SelectorError:
            return False

    def matches_to_pod(self, pod: Pod, ns: Namespace) -> bool:
        if not self.matches_to_namespace(ns):
            return False
        return self.pod_selector.matches(pod.labels)


@dataclass(frozen=True)
class ClusterThrottleSelector:
    """clusterthrottle_selector.go:26-56."""

    selector_terms: Tuple[ClusterThrottleSelectorTerm, ...] = ()

    def matches_to_namespace(self, ns: Namespace) -> bool:
        for term in self.selector_terms:
            if term.matches_to_namespace(ns):
                return True
        return False

    def matches_to_pod(self, pod: Pod, ns: Namespace) -> bool:
        for term in self.selector_terms:
            if term.matches_to_pod(pod, ns):
                return True
        return False


# ---------------------------------------------------------------------------
# Specs, statuses, CRD objects
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ThrottleSpecBase:
    """throttle_types.go:28-35 (+ the heterogeneity extension
    ``accelClassThresholds`` — see AccelClassThreshold)."""

    throttler_name: str = ""
    threshold: ResourceAmount = field(default_factory=ResourceAmount)
    temporary_threshold_overrides: Tuple[TemporaryThresholdOverride, ...] = ()
    accel_class_thresholds: Tuple[AccelClassThreshold, ...] = ()

    def accel_threshold_for(self, accel_class: Optional[str]) -> Optional[ResourceAmount]:
        """First accelClassThresholds entry naming ``accel_class`` (first
        wins, like the override merge), or None."""
        if not accel_class:
            return None
        for entry in self.accel_class_thresholds:
            if entry.accel_class == accel_class:
                return entry.threshold
        return None

    def next_override_happens_in(self, now: datetime) -> Optional[timedelta]:
        """throttle_types.go:37-63: soonest future begin/end boundary."""
        next_after: Optional[timedelta] = None

        def update(d: timedelta) -> None:
            nonlocal next_after
            if next_after is None or next_after > d:
                next_after = d

        for o in self.temporary_threshold_overrides:
            try:
                begin_t = o.begin_time()
            except RFC3339ParseError:
                continue
            if begin_t is not None and begin_t > now:
                update(begin_t - now)
            try:
                end_t = o.end_time()
            except RFC3339ParseError:
                continue
            if end_t is not None and end_t > now:
                update(end_t - now)
        return next_after

    def calculate_threshold(self, now: datetime) -> CalculatedThreshold:
        """throttle_types.go:65-106.

        First-wins merge per dimension across active overrides; if ANY
        override is active the merged result REPLACES the entire spec
        threshold (dimensions absent from the merge become absent, i.e.
        unchecked — throttle_types.go:96-98)."""
        active_found = False
        override_counts: Optional[int] = None
        override_requests: Dict[str, Fraction] = {}
        messages: List[str] = []
        for i, o in enumerate(self.temporary_threshold_overrides):
            try:
                is_active = o.is_active(now)
            except RFC3339ParseError as e:
                messages.append(f"index {i}: {e}")
                continue
            if is_active:
                active_found = True
                if override_counts is None and o.threshold.resource_counts is not None:
                    override_counts = o.threshold.resource_counts
                for rn, rq in (o.threshold.resource_requests or {}).items():
                    if rn not in override_requests:
                        override_requests[rn] = rq

        threshold = self.threshold
        if active_found:
            threshold = ResourceAmount(
                resource_counts=override_counts, resource_requests=override_requests
            )
        return CalculatedThreshold(
            threshold=threshold, calculated_at=now, messages=tuple(messages)
        )


@dataclass(frozen=True)
class ThrottleSpec(ThrottleSpecBase):
    selector: ThrottleSelector = field(default_factory=ThrottleSelector)


@dataclass(frozen=True)
class ClusterThrottleSpec(ThrottleSpecBase):
    selector: ClusterThrottleSelector = field(default_factory=ClusterThrottleSelector)


@dataclass(frozen=True)
class ThrottleStatus:
    """throttle_types.go:113-117 (shared by both kinds)."""

    calculated_threshold: CalculatedThreshold = field(default_factory=CalculatedThreshold)
    throttled: IsResourceAmountThrottled = field(default_factory=IsResourceAmountThrottled)
    used: ResourceAmount = field(default_factory=ResourceAmount)


class CheckThrottleStatus:
    """throttle_types.go:119-126 — exact reference status strings."""

    NOT_THROTTLED = "not-throttled"
    ACTIVE = "active"
    INSUFFICIENT = "insufficient"
    POD_REQUESTS_EXCEEDS_THRESHOLD = "pod-requests-exceeds-threshold"


def effective_threshold(spec_threshold: ResourceAmount, status: ThrottleStatus) -> ResourceAmount:
    """The threshold a check actually uses: status.calculatedThreshold once a
    reconcile has stamped calculatedAt, else spec.threshold
    (throttle_types.go:129-132). Single source of truth — the host oracle,
    the standalone tensor encoder, and the live device mirror all call this."""
    if status.calculated_threshold.calculated_at is not None:
        return status.calculated_threshold.threshold
    return spec_threshold


def _check_throttled_for(
    spec_threshold: ResourceAmount,
    status: ThrottleStatus,
    pod: Pod,
    reserved: ResourceAmount,
    is_throttled_on_equal: bool,
    step3_on_equal: bool,
    threshold_override: Optional[ResourceAmount] = None,
) -> str:
    """The ordered 4-state check (throttle_types.go:128-153).

    step3_on_equal is True for Throttle (hardcoded at throttle_types.go:143)
    and ``is_throttled_on_equal`` for ClusterThrottle
    (clusterthrottle_types.go:45) — the one asymmetry between the kinds.

    ``threshold_override`` (heterogeneity: a resolved per-accelerator-class
    threshold) replaces the effective threshold for steps 1/3/4; step 2's
    persisted flags stay class-agnostic by contract (AccelClassThreshold).
    """
    threshold = (
        threshold_override
        if threshold_override is not None
        else effective_threshold(spec_threshold, status)
    )

    pod_amount = resource_amount_of_pod(pod)

    # 1. the pod alone exceeds the threshold → it can never schedule
    if threshold.is_throttled(pod_amount, False).is_throttled_for(pod):
        return CheckThrottleStatus.POD_REQUESTS_EXCEEDS_THRESHOLD

    # 2. the persisted throttled flags already block this pod
    if status.throttled.is_throttled_for(pod):
        return CheckThrottleStatus.ACTIVE

    # 3. used + reserved saturates the threshold
    already_used = ResourceAmount().add(status.used).add(reserved)
    if threshold.is_throttled(already_used, step3_on_equal).is_throttled_for(pod):
        return CheckThrottleStatus.ACTIVE

    # 4. used + reserved + pod would overflow it
    used = ResourceAmount().add(status.used).add(pod_amount).add(reserved)
    if threshold.is_throttled(used, is_throttled_on_equal).is_throttled_for(pod):
        return CheckThrottleStatus.INSUFFICIENT

    return CheckThrottleStatus.NOT_THROTTLED


@dataclass(frozen=True)
class Throttle:
    """Namespaced CRD (throttle_types.go:163-169)."""

    name: str
    namespace: str = "default"
    uid: str = ""
    spec: ThrottleSpec = field(default_factory=ThrottleSpec)
    status: ThrottleStatus = field(default_factory=ThrottleStatus)

    @cached_property
    def key(self) -> str:
        # cached_property on a frozen dataclass: writes via the instance
        # __dict__ (no __setattr__), replace() builds a fresh instance so
        # the cache can never go stale; the f-string rebuilt per access
        # was ~13 hits per served decision and ~80 per cfg5 drain key
        return f"{self.namespace}/{self.name}"

    def check_throttled_for(
        self,
        pod: Pod,
        reserved: ResourceAmount,
        is_throttled_on_equal: bool,
        accel_class: Optional[str] = None,
    ) -> str:
        return _check_throttled_for(
            self.spec.threshold,
            self.status,
            pod,
            reserved,
            is_throttled_on_equal,
            step3_on_equal=True,  # throttle_types.go:143
            threshold_override=self.spec.accel_threshold_for(accel_class),
        )

    def with_status(self, status: ThrottleStatus) -> "Throttle":
        return replace(self, status=status)


@dataclass(frozen=True)
class ClusterThrottle:
    """Cluster-scoped CRD (clusterthrottle_types.go:66-72)."""

    name: str
    uid: str = ""
    spec: ClusterThrottleSpec = field(default_factory=ClusterThrottleSpec)
    status: ThrottleStatus = field(default_factory=ThrottleStatus)

    @cached_property
    def key(self) -> str:
        # Go types.NamespacedName{Namespace: "", Name: name}.String() — the
        # leading "/" appears in PreFilter reason strings (plugin.go:289-295).
        # Cached like Throttle.key (frozen-safe — see there).
        return f"/{self.name}"

    def check_throttled_for(
        self,
        pod: Pod,
        reserved: ResourceAmount,
        is_throttled_on_equal: bool,
        accel_class: Optional[str] = None,
    ) -> str:
        return _check_throttled_for(
            self.spec.threshold,
            self.status,
            pod,
            reserved,
            is_throttled_on_equal,
            step3_on_equal=is_throttled_on_equal,  # clusterthrottle_types.go:45
            threshold_override=self.spec.accel_threshold_for(accel_class),
        )

    def with_status(self, status: ThrottleStatus) -> "ClusterThrottle":
        return replace(self, status=status)


def throttle_names(objs: Sequence[Throttle]) -> List[str]:
    return [o.key for o in objs]


def cluster_throttle_names(objs: Sequence[ClusterThrottle]) -> List[str]:
    return [o.key for o in objs]
