"""Benchmark harness — the five BASELINE.json configs.

Prints ONE JSON line to stdout (the headline: single-pod PreFilter decision
latency against 100k-pod × 10k-throttle state on one chip); per-config
detail goes to stderr.

Timing methodology: this environment reaches the TPU through a network
tunnel whose dispatch round-trip (~30-80ms) dwarfs kernel times, and its
``block_until_ready`` does not reliably block. True device time is measured
by slope: run N data-dependent chained iterations inside ONE dispatch
(lax.fori_loop), materialize to host, and take (t(N2)-t(N1))/(N2-N1). The
tunnel RTT is reported separately so co-located numbers can be projected.

Run: python bench.py            (ambient platform — TPU in CI)
     python bench.py --quick    (scaled-down shapes for smoke runs)
"""

import json
import sys
import time
from datetime import datetime, timezone

sys.path.insert(0, ".")

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from kube_throttler_tpu.ops.check import check_step
from kube_throttler_tpu.ops.aggregate import aggregate_used, apply_pod_delta
from kube_throttler_tpu.ops.overrides import NS_MAX, NS_MIN, OverrideSchedule, calculate_thresholds
from kube_throttler_tpu.ops.schema import PodBatch, ThrottleState

NOW = datetime(2024, 1, 15, tzinfo=timezone.utc)
NOW_NS = np.int64(int(NOW.timestamp()) * 10**9)

GiB_m = 1024**3 * 1000  # 1Gi in milli-units


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# --------------------------------------------------------------- synthesis


def synth_state(rng, T, R, sat_frac=0.3):
    """Synthetic throttle state: thresholds over cpu/mem/gpu + pod counts;
    ``sat_frac`` of throttles already saturated."""
    thr_cnt = rng.integers(1, 50, T).astype(np.int64)
    thr_cnt_present = rng.random(T) < 0.8
    thr_req = np.zeros((T, R), dtype=np.int64)
    thr_req_present = np.zeros((T, R), dtype=bool)
    thr_req[:, 0] = rng.integers(1, 64, T) * 1000  # cpu cores (milli)
    thr_req[:, 1] = rng.integers(1, 256, T) * GiB_m  # memory
    thr_req[:, 2] = rng.integers(0, 8, T) * 1000  # gpu
    thr_req_present[:, :3] = rng.random((T, 3)) < 0.9

    saturated = rng.random(T) < sat_frac
    used_cnt = np.where(saturated, thr_cnt, (thr_cnt * rng.random(T) * 0.8)).astype(np.int64)
    frac = np.where(saturated[:, None], 1.0, rng.random((T, 1)) * 0.8)
    used_req = (thr_req * frac).astype(np.int64)
    used_cnt_present = used_cnt > 0
    used_req_present = thr_req_present & (rng.random((T, R)) < 0.95)

    st_req = used_req_present & (used_req >= thr_req) & thr_req_present
    return ThrottleState(
        valid=np.ones(T, dtype=bool),
        thr_cnt=thr_cnt,
        thr_cnt_present=thr_cnt_present,
        thr_req=thr_req,
        thr_req_present=thr_req_present,
        used_cnt=used_cnt,
        used_cnt_present=used_cnt_present,
        used_req=used_req,
        used_req_present=used_req_present,
        res_cnt=np.zeros(T, dtype=np.int64),
        res_cnt_present=np.zeros(T, dtype=bool),
        res_req=np.zeros((T, R), dtype=np.int64),
        res_req_present=np.zeros((T, R), dtype=bool),
        st_cnt_throttled=used_cnt_present & thr_cnt_present & (used_cnt >= thr_cnt),
        st_req_throttled=st_req,
        st_req_flag_present=thr_req_present,
    )


def synth_pods(rng, P, T, R, matches_per_pod=2):
    req = np.zeros((P, R), dtype=np.int64)
    present = np.zeros((P, R), dtype=bool)
    req[:, 0] = rng.integers(1, 8, P) * 100  # 100m..700m cpu
    req[:, 1] = rng.integers(1, 32, P) * (GiB_m // 4)
    present[:, :2] = True
    batch = PodBatch(valid=np.ones(P, dtype=bool), req=req, req_present=present)

    mask = np.zeros((P, T), dtype=bool)
    rows = np.repeat(np.arange(P), matches_per_pod)
    cols = rng.integers(0, T, P * matches_per_pod)
    mask[rows, cols] = True
    return batch, mask


# ------------------------------------------------------------------ timing


def _host_time(fn, repeats=3):
    """Wall time to a full host materialization (tunnel-honest)."""
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn()
        np.asarray(jax.tree_util.tree_leaves(out)[0])
        best = min(best, time.perf_counter() - t0)
    return best


def device_time_per_iter(make_chained, n1=2, n2=12):
    """Slope timing: chained(n) runs n data-dependent iterations in one
    dispatch; per-iteration device time = (t(n2)-t(n1))/(n2-n1)."""
    f1, f2 = make_chained(n1), make_chained(n2)
    _host_time(f1, repeats=1)  # compile
    _host_time(f2, repeats=1)
    t1, t2 = _host_time(f1), _host_time(f2)
    return max((t2 - t1) / (n2 - n1), 1e-9)


def measure_dispatch_rtt():
    x = jax.device_put(np.ones(8, dtype=np.int64))
    f = jax.jit(lambda x: x + 1)
    np.asarray(f(x))
    times = []
    for _ in range(15):
        t0 = time.perf_counter()
        np.asarray(f(x))
        times.append(time.perf_counter() - t0)
    return float(np.percentile(times, 50))


# ------------------------------------------------------------------ benches


def chained_check(state, batch, mask, n):
    """n data-dependent full check sweeps in one dispatch."""

    @jax.jit
    def run(state, batch, mask):
        def body(i, acc):
            b = PodBatch(
                valid=batch.valid,
                req=batch.req + acc % 2 + i,  # data-dependence blocks reordering
                req_present=batch.req_present,
            )
            counts, _ = check_step(state, b, mask)
            return acc + jnp.sum(counts, dtype=jnp.int64)

        return lax.fori_loop(0, n, body, jnp.int64(0))

    return lambda: run(state, batch, mask)


def bench_batched(rng, P, T, R, label):
    state = synth_state(rng, T, R)
    batch, mask = synth_pods(rng, P, T, R)
    device = jax.devices()[0]
    state = jax.device_put(state, device)
    batch = jax.device_put(batch, device)
    mask = jax.device_put(mask, device)

    per_iter = device_time_per_iter(lambda n: chained_check(state, batch, mask, n))
    dps = P / per_iter
    log(
        f"[{label}] batched check {P}x{T}x{R}: {per_iter*1e3:.2f}ms/sweep device time "
        f"-> {dps:,.0f} pod-decisions/sec ({P*T/per_iter:,.0f} pair-cells/sec)"
    )
    return state, batch, mask, dps, per_iter


def bench_single_pod(rng, state, T, R, label):
    """Single-pod PreFilter decision ([1,T] check) device latency."""
    pod_req = np.zeros((1, R), dtype=np.int64)
    pod_present = np.zeros((1, R), dtype=bool)
    pod_req[0, 0] = 300
    pod_present[0, 0] = True
    batch = PodBatch(valid=np.ones(1, dtype=bool), req=pod_req, req_present=pod_present)
    mask_row = np.zeros((1, T), dtype=bool)
    mask_row[0, rng.integers(0, T, 3)] = True
    device = jax.devices()[0]
    batch = jax.device_put(batch, device)
    mask_row = jax.device_put(mask_row, device)

    per_check = device_time_per_iter(
        lambda n: chained_check(state, batch, mask_row, n), n1=10, n2=200
    )
    log(f"[{label}] single-pod check vs T={T}: {per_check*1e3:.4f}ms device time per decision")
    return per_check * 1e3


def bench_pallas_sweep(rng, P, T, R, label):
    """Dense sweep via the tiled Pallas kernel (ops/pallas_check.py):
    int32-limb compares + VMEM tiling vs the XLA broadcast fusion."""
    from kube_throttler_tpu.ops.fastcheck import precompute_check_state
    from kube_throttler_tpu.ops.pallas_check import BP, BT, pallas_check_pods

    P = P + (-P) % BP
    T = T + (-T) % BT
    state = synth_state(rng, T, R)
    batch, mask = synth_pods(rng, P, T, R)
    device = jax.devices()[0]
    state = jax.device_put(state, device)
    batch = jax.device_put(batch, device)
    mask = jax.device_put(mask, device)
    pre = precompute_check_state(state)
    jax.block_until_ready(pre.resid)

    def make(n):
        @jax.jit
        def run(pre, batch, mask):
            def body(i, acc):
                b = PodBatch(
                    valid=batch.valid,
                    req=batch.req + acc % 2 + i,
                    req_present=batch.req_present,
                )
                st = pallas_check_pods.__wrapped__(pre, b, mask, False, True, False)
                return acc + jnp.sum(st == 1, dtype=jnp.int64)

            return lax.fori_loop(0, n, body, jnp.int64(0))

        return lambda: run(pre, batch, mask)

    per_iter = device_time_per_iter(make, n1=2, n2=8)
    log(
        f"[{label}] pallas sweep {P}x{T}x{R}: {per_iter*1e3:.2f}ms/sweep "
        f"-> {P/per_iter:,.0f} pod-decisions/sec ({P*T/per_iter/1e9:.1f}G pair-cells/sec)"
    )
    return per_iter


def bench_single_pod_indexed(rng, state, T, R, label, K=64):
    """The real PreFilter hot path: gather the pod's K affected-throttle rows
    (host index supplies them) and classify O(K*R) — T-independent."""
    from kube_throttler_tpu.ops.fastcheck import (
        fast_check_pod_packed,
        pack_check_state,
        precompute_check_state,
    )

    pre = pack_check_state(precompute_check_state(state))
    jax.block_until_ready(pre.vals)

    pod_req = np.zeros(R, dtype=np.int64)
    pod_present = np.zeros(R, dtype=bool)
    pod_req[0] = 300
    pod_present[0] = True
    idx = np.zeros(K, dtype=np.int32)
    valid = np.zeros(K, dtype=bool)
    idx[:3] = rng.integers(0, T, 3)
    valid[:3] = True
    device = jax.devices()[0]
    pod_req, pod_present, idx, valid = (
        jax.device_put(a, device) for a in (pod_req, pod_present, idx, valid)
    )

    def make(n):
        @jax.jit
        def run(pre, pod_req, pod_present, idx, valid):
            def body(i, acc):
                st = fast_check_pod_packed.__wrapped__(
                    pre, pod_req + acc % 2 + i, pod_present, idx, valid, False, True
                )
                return acc + jnp.sum(st == 1, dtype=jnp.int64)

            return lax.fori_loop(0, n, body, jnp.int64(0))

        return lambda: run(pre, pod_req, pod_present, idx, valid)

    per_check = device_time_per_iter(make, n1=10, n2=500)
    log(
        f"[{label}] indexed single-pod check (K={K} gathered of T={T}): "
        f"{per_check*1e6:.2f}us device time per decision"
    )
    return per_check * 1e3


def bench_streaming_batched(rng, T, R, label, n_events=1000):
    """Event-burst ingest: all n_events in ONE scatter dispatch."""
    from kube_throttler_tpu.ops.aggregate import apply_pod_deltas_batched

    used_cnt = np.asarray(rng.integers(0, 50, T), dtype=np.int64)
    used_req = np.asarray(rng.integers(0, 64, (T, R)), dtype=np.int64) * 1000
    contrib = np.asarray(rng.integers(0, 10, (T, R)), dtype=np.int32)
    K = 4
    ids = rng.integers(0, T, (n_events, K)).astype(np.int32)
    signs = rng.choice(np.array([-1, 1], dtype=np.int64), (n_events, K))
    pod_req = np.zeros((n_events, R), dtype=np.int64)
    pod_req[:, 0] = 100
    pod_present = np.zeros((n_events, R), dtype=bool)
    pod_present[:, 0] = True
    device = jax.devices()[0]
    args = [
        jax.device_put(a, device)
        for a in (used_cnt, used_req, contrib, ids, signs, pod_req, pod_present)
    ]

    def make(n):
        @jax.jit
        def run(used_cnt, used_req, contrib, ids, signs, pod_req, pod_present):
            def body(i, carry):
                uc, ur, co = carry
                uc, ur, co = apply_pod_deltas_batched.__wrapped__(
                    uc + i % 2, ur, co, ids, signs, pod_req, pod_present
                )
                return (uc, ur, co)

            uc, ur, co = lax.fori_loop(0, n, body, (used_cnt, used_req, contrib))
            return uc[0] + ur[0, 0] + co[0, 0]

        return lambda: run(*args)

    per_batch = device_time_per_iter(make, n1=2, n2=12)
    eps = n_events / per_batch
    log(
        f"[{label}] batched streaming deltas T={T}: {eps:,.0f} events/sec "
        f"device-side ({per_batch*1e3:.3f}ms per {n_events}-event batch)"
    )
    return eps


def bench_overrides(rng, T, O, R, label):
    ov_valid = rng.random((T, O)) < 0.8
    ov_begin = np.full((T, O), NS_MIN, dtype=np.int64)
    ov_end = np.full((T, O), NS_MAX, dtype=np.int64)
    active = rng.random((T, O)) < 0.5
    ov_begin[active] = NOW_NS - 3_600_000_000_000
    ov_end[active] = NOW_NS + 3_600_000_000_000
    ov_begin[~active] = NOW_NS + 3_600_000_000_000
    sched = OverrideSchedule(
        ov_valid=ov_valid,
        ov_begin=ov_begin,
        ov_end=ov_end,
        ov_cnt=rng.integers(1, 50, (T, O)).astype(np.int64),
        ov_cnt_present=rng.random((T, O)) < 0.5,
        ov_req=rng.integers(1, 64, (T, O, R)).astype(np.int64) * 1000,
        ov_req_present=rng.random((T, O, R)) < 0.5,
        spec_cnt=rng.integers(1, 50, T).astype(np.int64),
        spec_cnt_present=np.ones(T, dtype=bool),
        spec_req=rng.integers(1, 64, (T, R)).astype(np.int64) * 1000,
        spec_req_present=np.ones((T, R), dtype=bool),
    )
    sched = jax.device_put(sched, jax.devices()[0])

    def make(n):
        @jax.jit
        def run(sched):
            def body(i, acc):
                cnt, cnt_p, req, req_p = calculate_thresholds(sched, NOW_NS + i + acc % 2)
                return acc + jnp.sum(cnt) + jnp.sum(req[:, 0])

            return lax.fori_loop(0, n, body, jnp.int64(0))

        return lambda: run(sched)

    per_iter = device_time_per_iter(make)
    log(f"[{label}] threshold resolution T={T} O={O}: {per_iter*1e3:.3f}ms device time")
    return per_iter


def bench_streaming(rng, T, R, label, n_events=1000):
    """Streaming reconcile: scatter-add pod-churn deltas into used. All
    n_events applied as one chained scan (the device-side rate)."""
    used_cnt = np.asarray(rng.integers(0, 50, T), dtype=np.int64)
    used_req = np.asarray(rng.integers(0, 64, (T, R)), dtype=np.int64) * 1000
    contrib = np.asarray(rng.integers(0, 10, (T, R)), dtype=np.int32)
    K = 4
    ids = rng.integers(0, T, (n_events, K)).astype(np.int32)
    signs = rng.choice(np.array([-1, 1], dtype=np.int64), (n_events, K))
    pod_req = np.zeros((n_events, R), dtype=np.int64)
    pod_req[:, 0] = 100
    pod_present = np.zeros((n_events, R), dtype=bool)
    pod_present[:, 0] = True

    device = jax.devices()[0]
    args = [jax.device_put(a, device) for a in (used_cnt, used_req, contrib, ids, signs, pod_req, pod_present)]

    @jax.jit
    def run_all(used_cnt, used_req, contrib, ids, signs, pod_req, pod_present):
        def body(carry, ev):
            uc, ur, co = carry
            i, s, pr, pp = ev
            uc, ur, co = apply_pod_delta(uc, ur, co, i, s, pr, pp)
            return (uc, ur, co), None

        (uc, ur, co), _ = lax.scan(body, (used_cnt, used_req, contrib), (ids, signs, pod_req, pod_present))
        return uc, ur, co

    t = _host_time(lambda: run_all(*args), repeats=1)  # compile
    t = _host_time(lambda: run_all(*args))
    eps = n_events / t
    log(f"[{label}] streaming deltas T={T}: {eps:,.0f} events/sec device-side (target 1k/s)")
    return eps


def bench_example_scenario(label):
    """BASELINE config 1: the example/throttle.yaml t1 + walkthrough pods
    through the FULL plugin stack on the host-oracle path (the 'CPU
    PreFilter reference scenario' — what the reference's Go hot path does
    per attempt, here per-decision host latency)."""
    import yaml

    from kube_throttler_tpu.api.pod import Namespace
    from kube_throttler_tpu.api.serialization import object_from_dict
    from kube_throttler_tpu.engine.store import Store
    from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args

    store = Store()
    store.create_namespace(Namespace("default"))
    plugin = KubeThrottler(
        decode_plugin_args({"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}),
        store,
        use_device=False,
    )
    with open("example/throttle.yaml") as f:
        store.create_throttle(object_from_dict(yaml.safe_load(f)))
    pods = []
    with open("example/pods.yaml") as f:
        for doc in yaml.safe_load_all(f):
            pod = object_from_dict(doc)
            store.create_pod(pod)
            pods.append(pod)
    plugin.run_pending_once()

    n = 2000
    t0 = time.perf_counter()
    for i in range(n):
        plugin.pre_filter(pods[i % len(pods)])
    dt = time.perf_counter() - t0
    log(
        f"[{label}] example t1 + pods1-3, host-oracle PreFilter: "
        f"{dt/n*1e6:.1f}us/decision ({n/dt:,.0f} decisions/sec)"
    )
    plugin.stop()


def bench_selector_index(label, T=10_000, n_pods=200):
    """Host-side selector-mask maintenance (SURVEY hard part 3): per-pod-event
    row recompute against T compiled selector columns, native C++ vs Python."""
    import random

    from kube_throttler_tpu.api.pod import Namespace, make_pod
    from kube_throttler_tpu.api.types import (
        LabelSelector,
        ResourceAmount,
        Throttle,
        ThrottleSelector,
        ThrottleSelectorTerm,
        ThrottleSpec,
    )
    from kube_throttler_tpu.engine.index import SelectorIndex
    from kube_throttler_tpu.native import available

    rng = random.Random(0)
    throttles = [
        Throttle(
            name=f"t{i}",
            spec=ThrottleSpec(
                throttler_name="x",
                threshold=ResourceAmount.of(pod=1),
                selector=ThrottleSelector(
                    selector_terms=(
                        ThrottleSelectorTerm(
                            LabelSelector(match_labels={"grp": f"g{i % 500}"})
                        ),
                    )
                ),
            ),
        )
        for i in range(T)
    ]
    pods = [
        make_pod(f"p{i}", labels={"grp": f"g{rng.randrange(500)}"}) for i in range(n_pods)
    ]

    for use_native, name in ((True, "native C++"), (False, "python")):
        if use_native and not available():
            log(f"[{label}] native tier unavailable (no toolchain or KT_TPU_NO_NATIVE=1); python tier only")
            continue
        idx = SelectorIndex("throttle", pod_capacity=n_pods, throttle_capacity=T, use_native=use_native)
        idx.upsert_namespace(Namespace("default"))
        for thr in throttles:
            idx.upsert_throttle(thr)
        t0 = time.perf_counter()
        for pod in pods:
            idx.upsert_pod(pod)  # one mask-row recompute per pod event
        dt = (time.perf_counter() - t0) / n_pods
        log(f"[{label}] pod-event row recompute vs T={T} ({name}): {dt*1e6:.1f}us/event")


def main():
    quick = "--quick" in sys.argv
    scale = 10 if quick else 1
    rng = np.random.default_rng(0)
    log(f"devices: {jax.devices()}")

    rtt = measure_dispatch_rtt()
    log(f"dispatch round-trip (environment tunnel overhead): {rtt*1e3:.1f}ms")

    R = 8

    # config 1: the reference example scenario end-to-end (host path)
    bench_example_scenario("cfg1:example")
    bench_selector_index("host:index", T=10_000 // scale)

    # config 2: 1k pods x 100 throttles, 4 active dims
    bench_batched(rng, 1000 // scale, 100, R, "cfg2:1kx100")

    # config 3: 10k x 1k
    bench_batched(rng, 10_000 // scale, 1000 // scale, R, "cfg3:10kx1k")

    # config 4: 100k x 10k with overrides (the headline)
    P, T = 100_000 // scale, 10_000 // scale
    bench_overrides(rng, T, 4, R, "cfg4:overrides")
    state, batch, mask, dps, sweep_s = bench_batched(rng, P, T, R, "cfg4:100kx10k")
    try:
        bench_pallas_sweep(rng, P, T, R, "cfg4:100kx10k")
    except Exception as e:  # pallas needs the TPU mosaic path; CPU runs skip
        log(f"[cfg4:100kx10k] pallas sweep unavailable: {str(e)[:120]}")
    bench_single_pod(rng, state, T, R, "cfg4:100kx10k")
    single_ms = bench_single_pod_indexed(rng, state, T, R, "cfg4:100kx10k")

    # config 5: streaming reconcile
    bench_streaming(rng, T, R, "cfg5:streaming")
    bench_streaming_batched(rng, T, R, "cfg5:streaming")

    target_ms = 1.0  # BASELINE north star: <1ms p99 on one v5e-1
    single_ms = max(float(single_ms), 1e-4)  # slope noise floor
    print(
        json.dumps(
            {
                "metric": "PreFilter decision latency, single pod vs 100k-pod/10k-throttle state (device time, 1 chip)",
                "value": round(single_ms, 4),
                "unit": "ms",
                "vs_baseline": round(target_ms / single_ms, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
