"""Benchmark harness — the five BASELINE.json configs.

Prints ONE JSON line to stdout (the headline: single-pod PreFilter decision
latency against 100k-pod × 10k-throttle state on one chip); per-config
detail goes to stderr.

Timing methodology: this environment reaches the TPU through a network
tunnel whose dispatch round-trip (~30-80ms) dwarfs kernel times, and its
``block_until_ready`` does not reliably block. True device time is measured
by slope: run N data-dependent chained iterations inside ONE dispatch
(lax.fori_loop), materialize to host, and take (t(N2)-t(N1))/(N2-N1). The
tunnel RTT is reported separately so co-located numbers can be projected.

Resilience: the tunnel backend can be transiently unavailable. Before any
in-process backend touch, a subprocess probe retries ``jax.devices()`` with
bounded exponential backoff; if the platform never comes up the bench
re-execs itself on CPU (degraded, flagged in the JSON). Every config is
individually fenced so a single failure cannot cost the run its output;
and because the tunnel can also drop MID-RUN (wedging a blocking device
call forever, which no exception fence can catch), a global watchdog
thread (KT_BENCH_DEADLINE_S, default 1800s) emits the best-so-far JSON
line at the deadline and exits — rc=0 if a usable measurement (value>0)
made it out, rc=1 otherwise. The final JSON line is ALWAYS printed.

Run: python bench.py            (ambient platform — TPU in CI)
     python bench.py --quick    (scaled-down shapes for smoke runs)
"""

import json
import os
import subprocess
import sys
import threading
import time
import traceback
from datetime import datetime, timezone

sys.path.insert(0, ".")

import numpy as np

from kube_throttler_tpu.utils.platform import (
    enable_persistent_compilation_cache,
    honor_jax_platforms_env,
)

honor_jax_platforms_env()  # must run before the first backend init

import jax
import jax.numpy as jnp
from jax import lax

from kube_throttler_tpu.ops.check import check_step
from kube_throttler_tpu.ops.aggregate import aggregate_used, apply_pod_delta
from kube_throttler_tpu.ops.overrides import NS_MAX, NS_MIN, OverrideSchedule, calculate_thresholds
from kube_throttler_tpu.ops.schema import PodBatch, ThrottleState

NOW = datetime(2024, 1, 15, tzinfo=timezone.utc)
NOW_NS = np.int64(int(NOW.timestamp()) * 10**9)

GiB_m = 1024**3 * 1000  # 1Gi in milli-units


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ------------------------------------------------------- global watchdog
#
# The tunnel backend can drop MID-RUN, leaving a blocking device call stuck
# forever — a hang the per-config fences cannot catch (the exception never
# raises). The contract is ONE JSON line no matter what, so a deadline
# thread snapshots whatever has been measured so far and emits it. State
# the emitter needs is progressively published into RESULT_STATE by main().

RESULT_STATE: dict = {"detail": {}, "errors": {}}
_EMITTED = threading.Event()
_EMIT_LOCK = threading.Lock()
_DEADLINE: list = [None]  # [monotonic deadline] once main() sets it


def time_left() -> float:
    return float("inf") if _DEADLINE[0] is None else _DEADLINE[0] - time.monotonic()


def emit(out: dict) -> bool:
    """Print the one JSON line exactly once, whoever gets there first.

    Atomic test-and-set: the watchdog and the main thread can race here at
    the deadline boundary, and two JSON lines would break the driver's
    single-line contract.
    """
    with _EMIT_LOCK:
        if _EMITTED.is_set():
            return False
        _EMITTED.set()
    print(json.dumps(out), flush=True)
    return True


def _watchdog_main(margin: float = 30.0) -> None:
    while not _EMITTED.is_set():
        left = time_left() - margin
        if left <= 0:
            break
        time.sleep(min(left, 5.0))
    if _EMITTED.is_set():
        return
    log("WATCHDOG: deadline reached; emitting best-so-far result and exiting")
    RESULT_STATE["errors"]["watchdog"] = "global deadline hit; remaining configs skipped"
    try:
        out = build_result()
    except BaseException as e:  # noqa: BLE001 — last resort, never die silently
        out = {
            "metric": "bench deadline hit before any measurement",
            "value": -1.0,
            "unit": "ms",
            "vs_baseline": 0.0,
            "error": f"{e.__class__.__name__}: {str(e)[:200]}",
        }
    emit(out)
    # A wedged device call cannot be unwound; exit hard. rc=0 only if a
    # usable partial measurement made it out (same contract as __main__).
    os._exit(0 if out.get("value", -1.0) > 0 else 1)


def start_watchdog() -> None:
    try:
        budget = float(os.environ.get("KT_BENCH_DEADLINE_S", "1800"))
    except ValueError:
        budget = 1800.0  # malformed override must not kill the bench
    _DEADLINE[0] = time.monotonic() + budget
    t = threading.Thread(target=_watchdog_main, name="bench-watchdog", daemon=True)
    t.start()


# ------------------------------------------------------------- backend init


def ensure_backend(max_wait: float = 300.0) -> bool:
    """Probe backend availability in a SUBPROCESS with bounded retry/backoff.

    The in-process backend cache is poisoned permanently by one failed init,
    so never touch ``jax.devices()`` here until a throwaway process has
    proven the platform is up. Returns True when the probe succeeds; False
    when the deadline expires (caller degrades to CPU).
    """
    deadline = time.monotonic() + max_wait
    delay, attempt = 2.0, 0
    while True:
        attempt += 1
        try:
            probe = (
                f"import sys; sys.path.insert(0, {os.path.dirname(os.path.abspath(__file__))!r})\n"
                "from kube_throttler_tpu.utils.platform import honor_jax_platforms_env\n"
                "honor_jax_platforms_env()\n"
                "import jax; jax.devices()"
            )
            r = subprocess.run(
                [sys.executable, "-c", probe],
                capture_output=True,
                timeout=max(1.0, min(300.0, deadline - time.monotonic())),
            )
            if r.returncode == 0:
                if attempt > 1:
                    log(f"backend probe succeeded on attempt {attempt}")
                return True
            err = r.stderr.decode(errors="replace").strip().splitlines()
            log(f"backend probe attempt {attempt} failed: {err[-1] if err else 'rc!=0'}")
        except subprocess.TimeoutExpired:
            log(f"backend probe attempt {attempt} timed out")
        if time.monotonic() + delay > deadline:
            return False
        log(f"retrying backend probe in {delay:.0f}s")
        time.sleep(delay)
        delay = min(delay * 2, 60.0)


def init_devices_or_reexec():
    """First in-process backend touch, fenced. If it still fails after the
    probe said OK (tunnel dropped between probe and init), re-exec once on
    CPU so the run produces a (degraded) result instead of a stack trace."""
    try:
        return jax.devices()
    except Exception as e:  # backend cache is now poisoned; re-exec is the only recovery
        if os.environ.get("KT_BENCH_CPU_FALLBACK") == "1":
            raise
        log(f"in-process backend init failed ({str(e)[:200]}); re-exec on CPU")
        env = {**os.environ, "JAX_PLATFORMS": "cpu", "KT_BENCH_CPU_FALLBACK": "1"}
        # Carry the REMAINING deadline into the child: a fresh process would
        # re-read the full budget and the combined wall time could outlive
        # the external harness timeout — the exact hang-with-no-JSON-line
        # failure the watchdog exists to prevent.
        if time_left() != float("inf"):
            env["KT_BENCH_DEADLINE_S"] = str(max(60.0, time_left()))
        os.execvpe(sys.executable, [sys.executable, os.path.abspath(__file__), *sys.argv[1:]], env)


# --------------------------------------------------------------- synthesis


def synth_state(rng, T, R, sat_frac=0.3):
    """Synthetic throttle state: thresholds over cpu/mem/gpu + pod counts;
    ``sat_frac`` of throttles already saturated."""
    thr_cnt = rng.integers(1, 50, T).astype(np.int64)
    thr_cnt_present = rng.random(T) < 0.8
    thr_req = np.zeros((T, R), dtype=np.int64)
    thr_req_present = np.zeros((T, R), dtype=bool)
    thr_req[:, 0] = rng.integers(1, 64, T) * 1000  # cpu cores (milli)
    thr_req[:, 1] = rng.integers(1, 256, T) * GiB_m  # memory
    thr_req[:, 2] = rng.integers(0, 8, T) * 1000  # gpu
    thr_req_present[:, :3] = rng.random((T, 3)) < 0.9

    saturated = rng.random(T) < sat_frac
    used_cnt = np.where(saturated, thr_cnt, (thr_cnt * rng.random(T) * 0.8)).astype(np.int64)
    frac = np.where(saturated[:, None], 1.0, rng.random((T, 1)) * 0.8)
    used_req = (thr_req * frac).astype(np.int64)
    used_cnt_present = used_cnt > 0
    used_req_present = thr_req_present & (rng.random((T, R)) < 0.95)

    st_req = used_req_present & (used_req >= thr_req) & thr_req_present
    return ThrottleState(
        valid=np.ones(T, dtype=bool),
        thr_cnt=thr_cnt,
        thr_cnt_present=thr_cnt_present,
        thr_req=thr_req,
        thr_req_present=thr_req_present,
        used_cnt=used_cnt,
        used_cnt_present=used_cnt_present,
        used_req=used_req,
        used_req_present=used_req_present,
        res_cnt=np.zeros(T, dtype=np.int64),
        res_cnt_present=np.zeros(T, dtype=bool),
        res_req=np.zeros((T, R), dtype=np.int64),
        res_req_present=np.zeros((T, R), dtype=bool),
        st_cnt_throttled=used_cnt_present & thr_cnt_present & (used_cnt >= thr_cnt),
        st_req_throttled=st_req,
        st_req_flag_present=thr_req_present,
    )


def synth_pods(rng, P, T, R, matches_per_pod=2):
    req = np.zeros((P, R), dtype=np.int64)
    present = np.zeros((P, R), dtype=bool)
    req[:, 0] = rng.integers(1, 8, P) * 100  # 100m..700m cpu
    req[:, 1] = rng.integers(1, 32, P) * (GiB_m // 4)
    present[:, :2] = True
    batch = PodBatch(valid=np.ones(P, dtype=bool), req=req, req_present=present)

    mask = np.zeros((P, T), dtype=bool)
    rows = np.repeat(np.arange(P), matches_per_pod)
    cols = rng.integers(0, T, P * matches_per_pod)
    mask[rows, cols] = True
    return batch, mask


# ------------------------------------------------------------------ timing


def _host_time(fn, repeats=3):
    """Wall time to a full host materialization (tunnel-honest)."""
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn()
        np.asarray(jax.tree_util.tree_leaves(out)[0])
        best = min(best, time.perf_counter() - t0)
    return best


def device_time_per_iter(make_chained, n1=2, n2=12):
    """Slope timing: chained(n) runs n data-dependent iterations in one
    dispatch; per-iteration device time = (t(n2)-t(n1))/(n2-n1). The
    single-number (median) view of device_time_stats."""
    return device_time_stats(make_chained, n1=n1, n2=n2, samples=3)["p50"]


def device_time_stats(make_chained, n1=2, n2=12, samples=8):
    """Repeated paired-slope estimates → distribution of per-iteration device
    time. Each sample is an independent (t(n1), t(n2)) pair, so tunnel-RTT
    jitter common to both dispatches cancels in the difference.

    NOTE on what the percentiles mean: each slope sample averages (n2-n1)
    chained device iterations, so this is the distribution of the slope
    ESTIMATOR, not of individual decision latencies — per-decision device
    tail cannot be observed through a ~66 ms tunnel RTT. True per-call tail
    latency is measured separately on the host paths (host_percentiles).

    Returns {mean, p50, p99, cv, samples}; cv = std/mean of the slope
    samples — a noisy measurement (cv>0.5) is retried once with double the
    samples and the top/bottom outliers dropped, and cv is recomputed."""
    f1, f2 = make_chained(n1), make_chained(n2)
    _host_time(f1, repeats=1)  # compile
    _host_time(f2, repeats=1)

    def collect(k):
        est = []
        for _ in range(k):
            # min-of-3 per endpoint: a single ms-scale tunnel-RTT spike on one
            # dispatch would otherwise swing (or negate) a µs-scale slope
            t1 = _host_time(f1, repeats=3)
            t2 = _host_time(f2, repeats=3)
            est.append(max((t2 - t1) / (n2 - n1), 1e-9))
        return np.array(est)

    est = collect(samples)
    cv = float(est.std() / est.mean()) if est.mean() > 0 else 0.0
    if cv > 0.5:  # noisy measurement: double the sample count, trim outliers
        est = np.sort(np.concatenate([est, collect(samples)]))[1:-1]
        cv = float(est.std() / est.mean()) if est.mean() > 0 else 0.0
    return {
        "mean": float(est.mean()),
        "p50": float(np.percentile(est, 50)),
        "p99": float(np.percentile(est, 99)),
        "cv": cv,
        "samples": int(len(est)),
    }


def host_percentiles(fn, n, warmup=50, max_seconds=None):
    """True per-call latency distribution of a host-side function. With
    ``max_seconds`` the sample count adapts to the call cost (through the
    tunnel a single call can cost ~2 RTTs; 2000 sequential samples would
    take ~10 minutes) — sampling stops at the time budget, never below 200
    samples, so percentiles stay meaningful."""
    for _ in range(min(warmup, n)):
        fn()
    times = []
    deadline = time.perf_counter() + max_seconds if max_seconds else None
    for i in range(n):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
        if deadline is not None and len(times) >= 200 and time.perf_counter() > deadline:
            break
    arr = np.asarray(times)
    return {
        "mean": float(arr.mean()),
        "p50": float(np.percentile(arr, 50)),
        "p99": float(np.percentile(arr, 99)),
        "samples": len(times),
    }


def measure_dispatch_rtt():
    x = jax.device_put(np.ones(8, dtype=np.int64))
    f = jax.jit(lambda x: x + 1)
    np.asarray(f(x))
    times = []
    for _ in range(15):
        t0 = time.perf_counter()
        np.asarray(f(x))
        times.append(time.perf_counter() - t0)
    return float(np.percentile(times, 50))


# ------------------------------------------------------------------ benches


def chained_check(state, batch, mask, n):
    """n data-dependent full check sweeps in one dispatch."""

    @jax.jit
    def run(state, batch, mask):
        def body(i, acc):
            b = PodBatch(
                valid=batch.valid,
                req=batch.req + acc % 2 + i,  # data-dependence blocks reordering
                req_present=batch.req_present,
            )
            counts, _ = check_step(state, b, mask)
            return acc + jnp.sum(counts, dtype=jnp.int64)

        return lax.fori_loop(0, n, body, jnp.int64(0))

    return lambda: run(state, batch, mask)


def bench_batched(rng, P, T, R, label):
    state = synth_state(rng, T, R)
    batch, mask = synth_pods(rng, P, T, R)
    device = jax.devices()[0]
    state = jax.device_put(state, device)
    batch = jax.device_put(batch, device)
    mask = jax.device_put(mask, device)

    per_iter = device_time_per_iter(lambda n: chained_check(state, batch, mask, n))
    dps = P / per_iter
    log(
        f"[{label}] batched check {P}x{T}x{R}: {per_iter*1e3:.2f}ms/sweep device time "
        f"-> {dps:,.0f} pod-decisions/sec ({P*T/per_iter:,.0f} pair-cells/sec)"
    )
    return state, batch, mask, dps, per_iter


def bench_single_pod(rng, state, T, R, label):
    """Single-pod PreFilter decision ([1,T] check) device latency."""
    pod_req = np.zeros((1, R), dtype=np.int64)
    pod_present = np.zeros((1, R), dtype=bool)
    pod_req[0, 0] = 300
    pod_present[0, 0] = True
    batch = PodBatch(valid=np.ones(1, dtype=bool), req=pod_req, req_present=pod_present)
    mask_row = np.zeros((1, T), dtype=bool)
    mask_row[0, rng.integers(0, T, 3)] = True
    device = jax.devices()[0]
    batch = jax.device_put(batch, device)
    mask_row = jax.device_put(mask_row, device)

    per_check = device_time_per_iter(
        lambda n: chained_check(state, batch, mask_row, n), n1=10, n2=200
    )
    log(f"[{label}] single-pod check vs T={T}: {per_check*1e3:.4f}ms device time per decision")
    return per_check * 1e3


def bench_pallas_sweep(rng, P, T, R, label):
    """Dense sweep via the tiled Pallas kernel (ops/pallas_check.py):
    int32-limb compares + VMEM tiling vs the XLA broadcast fusion."""
    from kube_throttler_tpu.ops.fastcheck import precompute_check_state
    from kube_throttler_tpu.ops.pallas_check import BP, BT, pallas_check_pods

    P = P + (-P) % BP
    T = T + (-T) % BT
    state = synth_state(rng, T, R)
    batch, mask = synth_pods(rng, P, T, R)
    device = jax.devices()[0]
    state = jax.device_put(state, device)
    batch = jax.device_put(batch, device)
    mask = jax.device_put(mask, device)
    pre = precompute_check_state(state)
    jax.block_until_ready(pre.resid)

    def make(n):
        @jax.jit
        def run(pre, batch, mask):
            def body(i, acc):
                b = PodBatch(
                    valid=batch.valid,
                    req=batch.req + acc % 2 + i,
                    req_present=batch.req_present,
                )
                st = pallas_check_pods.__wrapped__(pre, b, mask, False, True, False)
                return acc + jnp.sum(st == 1, dtype=jnp.int64)

            return lax.fori_loop(0, n, body, jnp.int64(0))

        return lambda: run(pre, batch, mask)

    per_iter = device_time_per_iter(make, n1=2, n2=8)
    log(
        f"[{label}] pallas sweep {P}x{T}x{R}: {per_iter*1e3:.2f}ms/sweep "
        f"-> {P/per_iter:,.0f} pod-decisions/sec ({P*T/per_iter/1e9:.1f}G pair-cells/sec)"
    )
    return per_iter


def bench_donation(rng, P, T, label):
    """Buffer-donation on/off delta for the incremental device-cache refresh
    (VERDICT r3 weak #5 / r4 task 1). Measures a [P,T] bool cache updated by
    ``.at[rows].set()`` — the same pattern devicestate uses to refresh its
    device mask/pods/cols caches (devicestate.py ``_device_mask.at[rows]``).
    With ``donate_argnums`` XLA scatters into the input buffer in place;
    without it every refresh allocates a fresh P×T array and copies the
    unchanged rows (HBM-bandwidth-bound: ~P*T bytes per refresh).

    The production caches deliberately do NOT donate: versioned serving
    snapshots hold references to the pre-update buffer (devicestate
    ``device_state()``/``device_pods()``), and donating a still-referenced
    buffer deletes it under those readers. This entry quantifies what that
    safety costs per refresh, and what a single-writer path (no concurrent
    snapshot readers — e.g. the sharded tick's private columns) saves by
    donating. The aggregate/rebase path originally named by r3 weak #5 is
    host-resident since b8b02f4, so the cache refresh is the remaining
    device-side in-place candidate.

    Timing: donation only takes effect across dispatch boundaries (an
    in-jit fori_loop chain reuses buffers regardless), so this streams n
    sequential dependent dispatches and slope-times the stream; the final
    1-element slice materialization waits for the whole chain without
    downloading the P×T result."""
    rows_n = min(256, P)
    device = jax.devices()[0]

    def scatter(arr, rows, vals):
        return arr.at[rows].set(vals)

    variants = {
        "nodonate": jax.jit(scatter),
        "donate": jax.jit(scatter, donate_argnums=(0,)),
    }
    rows = jax.device_put(
        rng.integers(0, P, rows_n).astype(np.int32), device
    )
    vals = jax.device_put(np.ones((rows_n, T), dtype=bool), device)
    alloc = jax.jit(lambda: jnp.zeros((P, T), dtype=bool))  # on-device, no upload

    out = {}
    for name, fn in variants.items():

        def stream(n, fn=fn):
            def run():
                arr = alloc()
                for _ in range(n):
                    arr = fn(arr, rows, vals)
                return arr[0:1, 0]  # tiny materialization, waits on the chain

            return run

        stream(1)()  # compile both the alloc and the scatter
        t1 = _host_time(stream(4), repeats=3)
        t2 = _host_time(stream(24), repeats=3)
        # the donated scatter (256 rows in place) can slope-time below host
        # timer resolution; floor at 1µs so the ratio stays meaningful
        # ("≥Nx") instead of exploding on a sub-noise denominator
        out[name] = max((t2 - t1) / 20, 1e-6)
    speedup = out["nodonate"] / out["donate"]
    log(
        f"[{label}] donation delta on [{P}x{T}] row-refresh: "
        f"nodonate {out['nodonate']*1e3:.3f}ms/update, "
        f"donate {out['donate']*1e3:.3f}ms/update -> {speedup:.1f}x"
    )
    return {
        "donation_nodonate_ms": round(out["nodonate"] * 1e3, 4),
        "donation_donate_ms": round(out["donate"] * 1e3, 4),
        "donation_speedup": round(speedup, 2),
    }


def bench_single_pod_indexed(rng, state, T, R, label, K=64):
    """The real PreFilter hot path: gather the pod's K affected-throttle rows
    (host index supplies them) and classify O(K*R) — T-independent."""
    from kube_throttler_tpu.ops.fastcheck import (
        fast_check_pod_packed,
        pack_check_state,
        precompute_check_state,
    )

    pre = pack_check_state(precompute_check_state(state))
    jax.block_until_ready(pre.vals)

    pod_req = np.zeros(R, dtype=np.int64)
    pod_present = np.zeros(R, dtype=bool)
    pod_req[0] = 300
    pod_present[0] = True
    idx = np.zeros(K, dtype=np.int32)
    valid = np.zeros(K, dtype=bool)
    idx[:3] = rng.integers(0, T, 3)
    valid[:3] = True
    device = jax.devices()[0]
    pod_req, pod_present, idx, valid = (
        jax.device_put(a, device) for a in (pod_req, pod_present, idx, valid)
    )

    def make(n):
        @jax.jit
        def run(pre, pod_req, pod_present, idx, valid):
            def body(i, acc):
                st = fast_check_pod_packed.__wrapped__(
                    pre, pod_req + acc % 2 + i, pod_present, idx, valid, False, True
                )
                return acc + jnp.sum(st == 1, dtype=jnp.int64)

            return lax.fori_loop(0, n, body, jnp.int64(0))

        return lambda: run(pre, pod_req, pod_present, idx, valid)

    stats = device_time_stats(make, n1=10, n2=500, samples=12)
    log(
        f"[{label}] indexed single-pod check (K={K} gathered of T={T}): "
        f"{stats['mean']*1e6:.2f}us mean / {stats['p99']*1e6:.2f}us p99-of-slope device time "
        f"per decision (cv={stats['cv']:.3f}, {stats['samples']} slope samples)"
    )
    return stats


def bench_streaming_batched(rng, T, R, label, n_events=1000):
    """Event-burst ingest: all n_events in ONE scatter dispatch."""
    from kube_throttler_tpu.ops.aggregate import apply_pod_deltas_batched

    used_cnt = np.asarray(rng.integers(0, 50, T), dtype=np.int64)
    used_req = np.asarray(rng.integers(0, 64, (T, R)), dtype=np.int64) * 1000
    contrib = np.asarray(rng.integers(0, 10, (T, R)), dtype=np.int32)
    K = 4
    ids = rng.integers(0, T, (n_events, K)).astype(np.int32)
    signs = rng.choice(np.array([-1, 1], dtype=np.int64), (n_events, K))
    pod_req = np.zeros((n_events, R), dtype=np.int64)
    pod_req[:, 0] = 100
    pod_present = np.zeros((n_events, R), dtype=bool)
    pod_present[:, 0] = True
    device = jax.devices()[0]
    args = [
        jax.device_put(a, device)
        for a in (used_cnt, used_req, contrib, ids, signs, pod_req, pod_present)
    ]

    def make(n):
        @jax.jit
        def run(used_cnt, used_req, contrib, ids, signs, pod_req, pod_present):
            def body(i, carry):
                uc, ur, co = carry
                uc, ur, co = apply_pod_deltas_batched.__wrapped__(
                    uc + i % 2, ur, co, ids, signs, pod_req, pod_present
                )
                return (uc, ur, co)

            uc, ur, co = lax.fori_loop(0, n, body, (used_cnt, used_req, contrib))
            return uc[0] + ur[0, 0] + co[0, 0]

        return lambda: run(*args)

    per_batch = device_time_per_iter(make, n1=2, n2=12)
    eps = n_events / per_batch
    log(
        f"[{label}] batched streaming deltas T={T}: {eps:,.0f} events/sec "
        f"device-side ({per_batch*1e3:.3f}ms per {n_events}-event batch)"
    )
    return eps


def bench_overrides(rng, T, O, R, label):
    ov_valid = rng.random((T, O)) < 0.8
    ov_begin = np.full((T, O), NS_MIN, dtype=np.int64)
    ov_end = np.full((T, O), NS_MAX, dtype=np.int64)
    active = rng.random((T, O)) < 0.5
    ov_begin[active] = NOW_NS - 3_600_000_000_000
    ov_end[active] = NOW_NS + 3_600_000_000_000
    ov_begin[~active] = NOW_NS + 3_600_000_000_000
    sched = OverrideSchedule(
        ov_valid=ov_valid,
        ov_begin=ov_begin,
        ov_end=ov_end,
        ov_cnt=rng.integers(1, 50, (T, O)).astype(np.int64),
        ov_cnt_present=rng.random((T, O)) < 0.5,
        ov_req=rng.integers(1, 64, (T, O, R)).astype(np.int64) * 1000,
        ov_req_present=rng.random((T, O, R)) < 0.5,
        spec_cnt=rng.integers(1, 50, T).astype(np.int64),
        spec_cnt_present=np.ones(T, dtype=bool),
        spec_req=rng.integers(1, 64, (T, R)).astype(np.int64) * 1000,
        spec_req_present=np.ones((T, R), dtype=bool),
    )
    sched = jax.device_put(sched, jax.devices()[0])

    def make(n):
        @jax.jit
        def run(sched):
            def body(i, acc):
                cnt, cnt_p, req, req_p = calculate_thresholds(sched, NOW_NS + i + acc % 2)
                return acc + jnp.sum(cnt) + jnp.sum(req[:, 0])

            return lax.fori_loop(0, n, body, jnp.int64(0))

        return lambda: run(sched)

    per_iter = device_time_per_iter(make)
    log(f"[{label}] threshold resolution T={T} O={O}: {per_iter*1e3:.3f}ms device time")
    return per_iter


def bench_streaming(rng, T, R, label, n_events=1000):
    """Streaming reconcile: scatter-add pod-churn deltas into used. All
    n_events applied as one chained scan (the device-side rate)."""
    used_cnt = np.asarray(rng.integers(0, 50, T), dtype=np.int64)
    used_req = np.asarray(rng.integers(0, 64, (T, R)), dtype=np.int64) * 1000
    contrib = np.asarray(rng.integers(0, 10, (T, R)), dtype=np.int32)
    K = 4
    ids = rng.integers(0, T, (n_events, K)).astype(np.int32)
    signs = rng.choice(np.array([-1, 1], dtype=np.int64), (n_events, K))
    pod_req = np.zeros((n_events, R), dtype=np.int64)
    pod_req[:, 0] = 100
    pod_present = np.zeros((n_events, R), dtype=bool)
    pod_present[:, 0] = True

    device = jax.devices()[0]
    args = [jax.device_put(a, device) for a in (used_cnt, used_req, contrib, ids, signs, pod_req, pod_present)]

    @jax.jit
    def run_all(used_cnt, used_req, contrib, ids, signs, pod_req, pod_present):
        def body(carry, ev):
            uc, ur, co = carry
            i, s, pr, pp = ev
            uc, ur, co = apply_pod_delta(uc, ur, co, i, s, pr, pp)
            return (uc, ur, co), None

        (uc, ur, co), _ = lax.scan(body, (used_cnt, used_req, contrib), (ids, signs, pod_req, pod_present))
        return uc, ur, co

    t = _host_time(lambda: run_all(*args), repeats=1)  # compile
    t = _host_time(lambda: run_all(*args))
    eps = n_events / t
    log(f"[{label}] streaming deltas T={T}: {eps:,.0f} events/sec device-side (target 1k/s)")
    return eps


# the serving-rung measurement anchors moved to the package so the
# scenario engine's SLO gates and the bench ladder measure with ONE
# implementation (kube_throttler_tpu/scenarios/measure.py); the historical
# underscore names stay bound here for every rung below
from kube_throttler_tpu.scenarios.measure import (  # noqa: E402
    flip_band_mc as _flip_band_mc,
    flip_watch_of as _flip_watch_of,
    group_keys_of as _group_keys_of,
    lag_tracker as _lag_tracker,
    served_throttle as _served_throttle,
)


def build_served_stack(P, T, groups=500, label="served"):
    """The REAL daemon stack at scale: store events → device mirror →
    controllers → statuses, exactly what production serves from. Returns
    (store, plugin). Setup cost is logged per phase (it is the honest cost
    of cold-starting this state)."""
    import random

    from kube_throttler_tpu.api.pod import Namespace, make_pod
    from kube_throttler_tpu.engine.store import Store
    from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args

    rng = random.Random(0)
    store = Store()
    plugin = KubeThrottler(
        decode_plugin_args(
            {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
        ),
        store,
        use_device=True,
    )
    store.create_namespace(Namespace("default"))

    t0 = time.perf_counter()
    flip_mc = _flip_band_mc(P, groups)
    for i in range(T):
        store.create_throttle(_served_throttle(i, groups, flip_band_mc=flip_mc))
    t_thr = time.perf_counter() - t0
    log(f"[{label}] created {T} throttles in {t_thr:.1f}s "
        f"(flip band: every 24th cpu threshold at {flip_mc}m)")

    t0 = time.perf_counter()
    from dataclasses import replace as _replace

    for i in range(P):
        pod = make_pod(
            f"p{i}",
            labels={"grp": f"g{rng.randrange(groups)}"},
            requests={"cpu": f"{rng.randrange(1, 8) * 100}m"},
        )
        pod = _replace(pod, spec=_replace(pod.spec, node_name="node-1"))
        pod.status.phase = "Running"
        store.create_pod(pod)
    t_pods = time.perf_counter() - t0
    log(f"[{label}] created {P} bound pods in {t_pods:.1f}s "
        f"({t_pods/P*1e6:.0f}us/event through the live index)")

    t0 = time.perf_counter()
    n = plugin.run_pending_once()
    t_rec = time.perf_counter() - t0
    log(f"[{label}] initial reconcile of {n} keys in {t_rec:.1f}s "
        f"(batched device aggregates)")

    if plugin.device_manager is not None:
        t0 = time.perf_counter()
        nk = plugin.device_manager.prewarm()
        log(f"[{label}] prewarmed {nk} kernel shapes in {time.perf_counter()-t0:.1f}s")
    # same pre-serving step the daemon takes (cli.py): freeze the startup
    # heap so automatic full GCs never rescan the 100k-pod graph — without
    # it gen2 pauses (500-750ms at full scale) land inside reconcile
    # drains and dominate the flip-publication tail
    from kube_throttler_tpu.utils.gchygiene import freeze_startup_heap

    frozen = freeze_startup_heap()
    if frozen > 0:
        log(f"[{label}] gc hygiene: froze {frozen} startup objects; gen2 deferred")
    return store, plugin


def bench_served_prefilter(plugin, label, groups=500, n=2000):
    """(VERDICT r2 task 4a) END-TO-END plugin.pre_filter latency through
    DeviceStateManager.check_pod — lock, request encode, mask row, kernel
    dispatch, decode, reason strings — against the live state. This is the
    number BASELINE's north star names (<1ms p99 per decision)."""
    from kube_throttler_tpu.api.pod import make_pod

    probes = [
        make_pod(
            f"probe{i}",
            labels={"grp": f"g{i % groups}"},
            requests={"cpu": f"{(i % 7 + 1) * 100}m"},
        )
        for i in range(64)
    ]
    i = [0]

    def one():
        plugin.pre_filter(probes[i[0] % len(probes)])
        i[0] += 1

    # stability protocol (VERDICT r4 task 4): ≥3 interleaved repeats with a
    # cross-run CV in the JSON, so a single-core host's run-to-run variance
    # (~2× observed between rounds) is distinguishable from a real
    # regression inside one bench record instead of across rounds
    runs = []
    stats = None
    for _rep in range(3):
        s = host_percentiles(one, n // 3, max_seconds=40.0)
        runs.append(1.0 / s["mean"])
        if stats is None or s["p50"] < stats["p50"]:
            stats = s  # keep the least-interfered pass's percentiles
        time.sleep(0.05)  # yield: let background noise land between passes
    rates = np.asarray(runs)
    stats["decisions_per_sec_median"] = float(np.median(rates))
    stats["decisions_cv"] = float(rates.std() / rates.mean()) if rates.mean() else 0.0
    log(
        f"[{label}] SERVED pre_filter p50 {stats['p50']*1e3:.3f}ms / "
        f"p99 {stats['p99']*1e3:.3f}ms per decision; "
        f"{stats['decisions_per_sec_median']:,.0f} decisions/sec "
        f"single-threaded (median of {len(runs)} interleaved runs, "
        f"cross-run CV {stats['decisions_cv']:.3f})"
    )

    # thread scaling (VERDICT r2 task 5 done-bar): the device-state lock
    # covers only host-side snapshot grabs, so concurrent checkers should
    # scale until dispatch overhead saturates
    import threading as _threading

    def measure_threads(k, duration=2.0):
        stop = _threading.Event()
        counts = [0] * k

        def worker(idx):
            j = idx
            while not stop.is_set():
                plugin.pre_filter(probes[j % len(probes)])
                counts[idx] += 1
                j += k

        threads = [_threading.Thread(target=worker, args=(w,)) for w in range(k)]
        for th in threads:
            th.start()
        time.sleep(duration)
        stop.set()
        for th in threads:
            th.join(timeout=10)
        return sum(counts) / duration

    rate1 = measure_threads(1)
    rate4 = measure_threads(4)
    # the micro-batching front-end (plugin/coalesce.py): concurrent callers
    # share one fused dispatch per window — the designed scaling path for
    # interactive traffic (pre_filter_batch remains the bulk surface)
    co = plugin.coalescer()
    co.pre_filter(probes[0])  # compile the (B,K) rungs the batch will hit

    def measure_threads_coalesced(k, duration=2.0):
        stop = _threading.Event()
        counts = [0] * k

        def worker(idx):
            j = idx
            while not stop.is_set():
                co.pre_filter(probes[j % len(probes)])
                counts[idx] += 1
                j += k

        threads = [_threading.Thread(target=worker, args=(w,)) for w in range(k)]
        for th in threads:
            th.start()
        time.sleep(duration)
        stop.set()
        for th in threads:
            th.join(timeout=10)
        return sum(counts) / duration

    rate4_co = measure_threads_coalesced(4)
    log(
        f"[{label}] served check throughput: {rate1:,.0f}/s x1 thread, "
        f"{rate4:,.0f}/s x4 threads (scaling {rate4/max(rate1,1e-9):.2f}x); "
        f"{rate4_co:,.0f}/s x4 threads COALESCED "
        f"({rate4_co/max(rate1,1e-9):.2f}x of 1-thread direct)"
    )
    return stats, rate1, rate4, rate4_co


def bench_coalesce_crossover(plugin, label, dispatch_ms=1.0, threads=4, duration=2.0):
    """(VERDICT r5 rec 6) The coalescer's designed win condition, emulated:
    per-dispatch cost ≥1ms — the shape of a remote-accelerator tunnel round
    trip, where every direct pre_filter pays TWO blocking dispatches (one
    per kind) while the coalescer amortizes two across a whole window's
    batch. The manager's check entry points are wrapped with a sleep of
    ``dispatch_ms`` per dispatch (sleep releases the GIL, exactly like a
    blocking device read), 4-thread direct vs coalesced throughput is
    measured, and the wrappers are removed. On this single-core CPU host
    the UN-emulated comparison loses (~0.4× r5) — this measures the
    crossover itself, empirically, instead of asserting it."""
    import threading as _threading

    from kube_throttler_tpu.api.pod import make_pod

    dm = plugin.device_manager
    probes = [
        make_pod(
            f"xprobe{i}",
            labels={"grp": f"g{i % 500}"},
            requests={"cpu": f"{(i % 7 + 1) * 100}m"},
        )
        for i in range(64)
    ]
    orig_pod, orig_multi = dm.check_pod, dm.check_pods_multi
    delay = dispatch_ms / 1e3

    def slow_pod(*a, **k):
        time.sleep(delay)
        return orig_pod(*a, **k)

    def slow_multi(*a, **k):
        time.sleep(delay)
        return orig_multi(*a, **k)

    def measure(fn):
        stop = _threading.Event()
        counts = [0] * threads

        def worker(idx):
            j = idx
            while not stop.is_set():
                fn(probes[j % len(probes)])
                counts[idx] += 1
                j += threads

        ts = [_threading.Thread(target=worker, args=(w,)) for w in range(threads)]
        for t in ts:
            t.start()
        time.sleep(duration)
        stop.set()
        for t in ts:
            t.join(timeout=10)
        return sum(counts) / duration

    co = plugin.coalescer()
    plugin.pre_filter(probes[0])
    co.pre_filter(probes[0])  # warm both paths before arming the delay
    dm.check_pod, dm.check_pods_multi = slow_pod, slow_multi
    try:
        direct = measure(plugin.pre_filter)
        coalesced = measure(co.pre_filter)
    finally:
        dm.check_pod, dm.check_pods_multi = orig_pod, orig_multi
    ratio = coalesced / max(direct, 1e-9)
    log(
        f"[{label}] COALESCE CROSSOVER (emulated {dispatch_ms:.1f}ms/dispatch, "
        f"{threads} threads): direct {direct:,.0f}/s vs coalesced "
        f"{coalesced:,.0f}/s -> {ratio:.2f}x "
        f"({'coalescer wins' if ratio > 1 else 'direct wins'})"
    )
    return {
        "dispatch_ms": dispatch_ms,
        "direct_per_sec": direct,
        "coalesced_per_sec": coalesced,
        "ratio": ratio,
    }


def bench_served_batch(plugin, label, iters=5):
    """Bulk triage through the SERVED surface: plugin.pre_filter_batch
    classifies every stored pod against both kinds' full state in one
    coherent snapshot (two device dispatches). The per-pod cost amortizes
    the dispatch across the whole store — the batched counterpart of the
    per-decision served p99."""
    out = plugin.pre_filter_batch()  # warm (compiles the batch kernels)
    n = len(out["schedulable"])
    before = {
        ph: (plugin.tracer.snapshot(ph) or {"sum": 0.0, "count": 0})
        for ph in ("batch_dispatch", "batch_merge")
    }
    t0 = time.perf_counter()
    for _ in range(iters):
        out = plugin.pre_filter_batch()
    dt = (time.perf_counter() - t0) / iters
    pods_per_sec = n / dt if dt else 0.0
    phases = {}
    for ph, b in before.items():
        s = plugin.tracer.snapshot(ph)
        if s and s["count"] > b["count"]:
            phases[ph] = (s["sum"] - b["sum"]) / (s["count"] - b["count"])
    split = ", ".join(f"{ph}={v*1e3:.1f}ms" for ph, v in phases.items())
    log(
        f"[{label}] SERVED pre_filter_batch: {n} pods in {dt*1e3:.1f}ms "
        f"({pods_per_sec:,.0f} pod-verdicts/sec, one snapshot per call; "
        f"phase split: {split or 'n/a'} — dispatch is the sparse [P,K] "
        f"gather kernel, merge is the AND across kinds + ns routing)"
    )
    return {"pods": n, "secs": dt, "pods_per_sec": pods_per_sec}


def bench_served_tick(plugin, label):
    """The fused reconcile+PreFilter sweep (`plugin.full_tick_sharded`, the
    POST /v1/tick surface) on one device: override-resolved thresholds,
    used re-aggregation, throttled flags, and the full [P,T] admission
    classification for BOTH kinds from one coherent snapshot. The
    freshest-possible whole-cluster verdict in a single device program."""
    plugin.full_tick_sharded(1)  # warm/compile
    tracer = plugin.device_manager.tracer
    phases = ("tick_snapshot", "tick_encode", "tick_device")
    before = {
        ph: (tracer.snapshot(ph) or {"sum": 0.0, "count": 0}) for ph in phases
    }
    t0 = time.perf_counter()
    out = plugin.full_tick_sharded(1)
    dt = time.perf_counter() - t0
    parts = []
    for ph in phases:
        s = tracer.snapshot(ph)
        if s and s["count"] > before[ph]["count"]:
            parts.append(f"{ph.removeprefix('tick_')}={1e3*(s['sum']-before[ph]['sum']):.1f}ms")
    log(
        f"[{label}] SERVED full tick (1 device): {len(out['schedulable'])} pods "
        f"x both kinds, fused reconcile+classify in {dt*1e3:.0f}ms "
        f"(phases: {', '.join(parts) or 'n/a'}; device phase is the sparse "
        f"[P,K] gather step on a 1x1 mesh, the dense shard_map program on "
        f"real meshes)"
    )
    return dt


def _drive_pod_churn(store, group_keys, pending, pend_lock, rng, duration, pace_hz,
                     flip_state=None, apply=None):
    """The cfg5 churn generator, SHARED by the in-process and remote-wire
    serving benches so their lag numbers stay comparable: paced pod
    updates that are REAL state changes every time — the cpu value always
    differs from the last written one (seeded from the pod's actual stored
    request, so even a pod's first update cannot be a no-op that leaves a
    stale pending timestamp poisoning later lag samples). Every event
    pre-registers its group's throttle keys in ``pending`` for the
    event→status-commit pairing.

    ``flip_state`` = (flip_watch, run_sums, flip_pending) arms
    crossing-anchored flip stamping: the generator maintains each group's
    running cpu sum and, when an update moves the sum across a watched
    throttle's threshold, stamps ``flip_pending[key]`` — the event that
    actually made the published flag wrong (see ``_lag_tracker``). Returns
    (n_events, fire-window seconds, crossings stamped).

    ``apply`` overrides how an updated pod reaches the store (default: the
    direct ``store.update_pod`` call) — the micro-batch sweep passes the
    ingest pipeline's submit here."""
    from dataclasses import replace as _replace

    from kube_throttler_tpu.api.pod import make_pod
    from kube_throttler_tpu.resourcelist import pod_request_resource_list

    pods = store.list_pods()
    if apply is None:
        apply = store.update_pod
    cur_cpu: dict = {}  # pod name → last cpu we wrote
    flip_watch, run_sums, flip_pending = flip_state or ({}, {}, {})
    n_crossings = 0
    n_events = 0
    t_start = time.perf_counter()
    deadline = t_start + duration
    while time.perf_counter() < deadline:
        if pace_hz:
            next_at = t_start + n_events / pace_hz
            delay = next_at - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
        pod = pods[rng.randrange(len(pods))]
        g = pod.labels["grp"]
        prev = cur_cpu.get(pod.name)
        if prev is None:  # seed from the pod's actual stored request
            stored = pod_request_resource_list(pod).get("cpu")
            prev = int(stored * 1000) if stored else 0
        new_cpu = rng.randrange(1, 8) * 100
        if new_cpu == prev:
            new_cpu = new_cpu % 700 + 100
        cur_cpu[pod.name] = new_cpu
        updated = make_pod(pod.name, labels=pod.labels, requests={"cpu": f"{new_cpu}m"})
        updated = _replace(updated, spec=_replace(updated.spec, node_name="node-1"))
        updated.status.phase = "Running"
        now = time.perf_counter()
        with pend_lock:
            for key in group_keys.get(g, ()):
                pending.setdefault(key, now)
            watch = flip_watch.get(g)
            if watch:
                s_old = run_sums.get(g, 0)
                s_new = s_old + new_cpu - prev
                run_sums[g] = s_new
                for key, thr_mc in watch:
                    if (s_old >= thr_mc) != (s_new >= thr_mc):
                        flip_pending[key] = now  # latest crossing wins
                        n_crossings += 1
        apply(updated)
        n_events += 1
    return n_events, time.perf_counter() - t_start, n_crossings


def bench_served_streaming(
    store, plugin, label, groups=500, duration=5.0, pace_hz=0.0,
    ingest_batch=None,
):
    """(VERDICT r2 task 4b) BASELINE cfg5 driven as store events through the
    CONTROLLERS: pod churn with workers running; reports the sustained
    pipeline rate and the event→status-commit lag (time from the first
    store event touching a throttle to the status write that reflects it —
    the reference's watch→reconcile→UpdateStatus latency,
    throttle_controller.go:84-211).

    ``pace_hz=0`` fires at max rate (measures CAPACITY; lag there reflects
    saturation backlog). ``pace_hz=1000`` fires at the BASELINE target rate
    (measures steady-state status-write lag under the nominal load).

    ``ingest_batch`` routes the churn through the micro-batched ingest
    pipeline (engine/ingest.py): ``"adaptive"`` or a fixed batch size; None
    keeps the direct per-event store calls (the PR 2 comparison rung)."""
    import random
    import threading as _threading
    from dataclasses import replace as _replace

    from kube_throttler_tpu.api.pod import make_pod
    from kube_throttler_tpu.engine.store import EventType

    rng = random.Random(1)
    # key → time of the first event not yet reflected in a status write
    pending, flip_pending, pend_lock, lags, flip_lags, _flip_walls, on_throttle_write = (
        _lag_tracker()
    )
    group_keys = _group_keys_of(store)
    flip_watch, run_sums = _flip_watch_of(store)
    store.add_event_handler("Throttle", on_throttle_write, replay=False)
    pipeline = None
    apply = None
    if ingest_batch is not None:
        from kube_throttler_tpu.engine.ingest import MicroBatchIngest

        pipeline = MicroBatchIngest(store, max_batch=64, batch_policy=ingest_batch)
        apply = lambda pod: pipeline.submit("update", "Pod", pod)  # noqa: E731
    plugin.start()
    try:
        n_events, t_fired, n_crossings = _drive_pod_churn(
            store, group_keys, pending, pend_lock, rng, duration, pace_hz,
            flip_state=(flip_watch, run_sums, flip_pending), apply=apply,
        )
        t_start = time.perf_counter() - t_fired
        # drain: the ingest queue first, then both workqueues, then writes
        if pipeline is not None:
            pipeline.flush(timeout=60.0)
        for ctr in (plugin.throttle_ctr, plugin.cluster_throttle_ctr):
            while len(ctr.workqueue) > 0:
                time.sleep(0.02)
        time.sleep(0.2)
        t_total = time.perf_counter() - t_start
    finally:
        # workers stay up (the caller may run another window and owns
        # plugin.stop() — a stopped workqueue is terminally shut down)
        store.remove_event_handler("Throttle", on_throttle_write)
        if pipeline is not None:
            pipeline.stop()

    n_applied = n_events
    if pipeline is not None:
        # capacity must count APPLIED events: at open-loop max rate the
        # bounded ingest queue drop-oldest-sheds — submitted ≠ ingested
        ps0 = pipeline.stats()
        n_applied = ps0["events_applied"]
    eps = n_applied / t_total
    lag_arr = np.asarray(lags) if lags else np.asarray([0.0])
    flip_arr = np.asarray(flip_lags) if flip_lags else np.asarray([0.0])
    result = {
        "events_per_sec": eps,
        # the rate the generator actually achieved DURING the window —
        # for paced runs this shows whether ingest kept the requested pace
        # (events_per_sec also amortizes the post-window drain tail, which
        # under-reads steady-state pacing by the drain fraction)
        "fired_events_per_sec": n_events / t_fired,
        "events_applied": n_applied,
        "lag_p50_ms": float(np.percentile(lag_arr, 50)) * 1e3,
        "lag_p99_ms": float(np.percentile(lag_arr, 99)) * 1e3,
        "status_writes": len(lags),
        # flip lag: crossing-event → flag-visible for writes that changed
        # throttled/calculatedThreshold ([0.0] sentinel when
        # flip_samples == 0 — don't read the percentiles then)
        "flip_lag_p50_ms": float(np.percentile(flip_arr, 50)) * 1e3,
        "flip_lag_p99_ms": float(np.percentile(flip_arr, 99)) * 1e3,
        "flip_samples": len(flip_lags),
        "flip_crossings": n_crossings,
    }
    if pipeline is not None:
        ps = pipeline.stats()
        result["ingest_batches"] = ps["batches"]
        result["ingest_mean_batch"] = round(
            ps["events_applied"] / max(ps["batches"], 1), 2
        )
        result["ingest_max_batch"] = ps["max_batch_seen"]
        result["ingest_dropped"] = ps["dropped"]
    mode = f"paced {pace_hz:,.0f}/s" if pace_hz else "max rate"
    log(
        f"[{label}] cfg5 THROUGH CONTROLLERS ({mode}): {n_events} events in "
        f"{t_total:.2f}s -> {eps:,.0f} events/sec sustained incl. drain "
        f"({result['fired_events_per_sec']:,.0f}/s during the fire window of "
        f"{t_fired:.2f}s); event->status-commit lag p50 "
        f"{result['lag_p50_ms']:.1f}ms / p99 {result['lag_p99_ms']:.1f}ms "
        f"over {len(lags)} status writes; FLIP lag p50 "
        f"{result['flip_lag_p50_ms']:.1f}ms / p99 {result['flip_lag_p99_ms']:.1f}ms "
        f"over {len(flip_lags)} flips from {n_crossings} crossings "
        f"(target: 1k events/sec, flip p99 <150ms)"
    )
    return result


def bench_ingest_burst(store, plugin, label, n=40_000, policy="adaptive", repeats=2):
    """Burst-drain ingest capacity: N real churn events are PRE-BUILT
    (producer cost off the clock) and preloaded into the micro-batch
    queue; the measurement is how fast the engine fully digests them —
    pipeline apply through reconcile-drain to empty workqueues. This is
    the clean capacity number: the open-loop max-rate window measures a
    producer/pipeline GIL fight plus drop-oldest shedding once the queue
    caps, neither of which is engine capacity.

    ``repeats``: capacity is a supremum — single-core GIL scheduling
    swings identical consecutive runs by up to ~1.5× (measured), and
    noise only subtracts — so the rung runs ``repeats`` times and reports
    the BEST, with every run recorded under ``runs``."""
    import random
    from dataclasses import replace as _replace

    from kube_throttler_tpu.api.pod import make_pod
    from kube_throttler_tpu.engine.ingest import MicroBatchIngest
    from kube_throttler_tpu.resourcelist import pod_request_resource_list

    rng = random.Random(4)
    pods = store.list_pods()
    cur_cpu: dict = {}

    def _mk_ops():
        ops = []
        for _ in range(n):
            pod = pods[rng.randrange(len(pods))]
            prev = cur_cpu.get(pod.name)
            if prev is None:
                stored = pod_request_resource_list(pod).get("cpu")
                prev = int(stored * 1000) if stored else 0
            new_cpu = rng.randrange(1, 8) * 100
            if new_cpu == prev:
                new_cpu = new_cpu % 700 + 100
            cur_cpu[pod.name] = new_cpu
            updated = make_pod(
                pod.name, labels=pod.labels, requests={"cpu": f"{new_cpu}m"}
            )
            updated = _replace(updated, spec=_replace(updated.spec, node_name="node-1"))
            updated.status.phase = "Running"
            ops.append(("update", "Pod", updated))
        return ops

    plugin.start()
    runs = []
    for rep in range(max(1, int(repeats))):
        ops = _mk_ops()
        pipeline = MicroBatchIngest(store, max_batch=64, batch_policy=policy, maxsize=n)
        t0 = time.perf_counter()
        pipeline.submit_many(ops)
        ok = pipeline.flush(timeout=300.0)
        t_apply = time.perf_counter() - t0
        for ctr in (plugin.throttle_ctr, plugin.cluster_throttle_ctr):
            while len(ctr.workqueue) > 0:
                time.sleep(0.02)
        time.sleep(0.2)
        t_total = time.perf_counter() - t0
        st = pipeline.stats()
        pipeline.stop()
        run = {
            "events": n,
            "flushed": ok,
            "apply_events_per_sec": round(n / t_apply),
            "events_per_sec_sustained": round(st["events_applied"] / t_total),
            "ingest_mean_batch": round(st["events_applied"] / max(st["batches"], 1), 2),
            "dropped": st["dropped"],
        }
        runs.append(run)
        log(
            f"[{label}] ingest BURST ({policy}, run {rep + 1}/{repeats}): {n} "
            f"events applied in {t_apply:.2f}s ({run['apply_events_per_sec']:,}/s "
            f"through the pipeline), fully reconciled in {t_total:.2f}s -> "
            f"{run['events_per_sec_sustained']:,} events/s sustained "
            f"(mean batch {run['ingest_mean_batch']})"
        )
    result = dict(max(runs, key=lambda r: r["events_per_sec_sustained"]))
    result["runs"] = runs
    return result


def bench_ingest_sweep(store, plugin, label, slo_pace=3300.0, duration=8.0):
    """PR 5 micro-batched ingest sweep over the full-scale capacity window:

    - ``direct`` — per-event store calls at max rate, the PR 2 comparison
      rung (the producer applies inline, so its fired rate IS the
      engine's per-event ceiling);
    - ``fixed64`` / ``adaptive`` — burst-drain capacity through the
      micro-batch pipeline at a fixed 64-event rung and the adaptive
      policy (see bench_ingest_burst — the clean "what can the engine
      digest" number);
    - ``adaptive-slo`` — the adaptive batcher PACED at ``slo_pace``: the
      sustained rate the pipeline holds while the flip-publication SLO
      (p99 ≤ 150ms) is met — "how fast can it go while admission-relevant
      flips stay fresh". The pace sits below the saturation knee on
      purpose: at the knee, queueing is bistable and the flip tail with
      it (the open-loop rungs document the over-the-knee regime).
    """
    out: dict = {"rungs": {}}
    # warmup (not recorded): the first window after stack build pays cold
    # code paths — measured ~1.4× slower than the identical next burst
    bench_ingest_burst(store, plugin, f"{label}:warmup", n=8_000, repeats=1)
    s = bench_served_streaming(
        store, plugin, f"{label}:direct", duration=duration, ingest_batch=None
    )
    out["rungs"]["direct"] = {
        "events_per_sec_sustained": round(s["events_per_sec"]),
        "events_per_sec_fired": round(s["fired_events_per_sec"]),
        "flip_lag_p50_ms": round(s["flip_lag_p50_ms"], 1),
        "flip_lag_p99_ms": round(s["flip_lag_p99_ms"], 1),
        "flip_samples": s["flip_samples"],
        "lag_p99_ms": round(s["lag_p99_ms"], 1),
        "pace_hz": 0.0,
    }
    for name, policy in (("fixed64", 64), ("adaptive", "adaptive")):
        out["rungs"][name] = bench_ingest_burst(
            store, plugin, f"{label}:{name}", policy=policy
        )
    # SLO knee search: the engine sits at ~85-95% utilization at these
    # paces on one core, where queueing is bistable run to run — so the
    # sweep measures a short ladder of paces and keeps the FASTEST rung
    # whose flip p99 met the 150ms SLO (every attempt is recorded).
    attempts = []
    best = None
    for pace in (slo_pace, slo_pace - 200.0, slo_pace - 400.0):
        s = bench_served_streaming(
            store, plugin, f"{label}:adaptive-slo@{pace:.0f}",
            duration=duration + 7.0, pace_hz=pace, ingest_batch="adaptive",
        )
        att = {
            "events_per_sec_sustained": round(s["events_per_sec"]),
            "events_per_sec_fired": round(s["fired_events_per_sec"]),
            "flip_lag_p50_ms": round(s["flip_lag_p50_ms"], 1),
            "flip_lag_p99_ms": round(s["flip_lag_p99_ms"], 1),
            "flip_samples": s["flip_samples"],
            "lag_p99_ms": round(s["lag_p99_ms"], 1),
            "pace_hz": pace,
            "ingest_mean_batch": s.get("ingest_mean_batch"),
        }
        attempts.append(att)
        if att["flip_lag_p99_ms"] <= 150.0 and (
            best is None
            or att["events_per_sec_sustained"] > best["events_per_sec_sustained"]
        ):
            best = att
    if best is None:  # nothing met the SLO: report the lowest-tail attempt
        best = min(attempts, key=lambda a: a["flip_lag_p99_ms"])
    out["rungs"]["adaptive-slo"] = dict(best)
    out["slo_attempts"] = attempts
    return out


def run_ingest_sweep() -> None:
    """``python bench.py --ingest-sweep``: the PR 5 acceptance artifact —
    full-scale (100k×10k) capacity sweep, written to BENCH_PR5_<platform>_
    <stamp>.json next to the PR 2 record, with the PR 2 reference numbers
    embedded for side-by-side reading."""
    platform = "cpu"
    try:
        import jax

        platform = jax.devices()[0].platform
    except Exception:
        pass
    store, plugin = build_served_stack(100_000, 10_000, label="pr5-sweep")
    try:
        sweep = bench_ingest_sweep(store, plugin, "pr5-sweep")
    finally:
        plugin.stop()
    # the PR 2 reference (committed record), embedded for comparison
    ref = {}
    try:
        import glob

        ref_files = sorted(glob.glob("BENCH_PR2_*.json"))
        if ref_files:
            with open(ref_files[-1]) as f:
                pr2 = json.load(f)
            ref = {
                "file": ref_files[-1],
                "fullscale_cfg5_maxrate_events_per_sec": pr2.get(
                    "fullscale_cfg5_maxrate_events_per_sec"
                ),
                "fullscale_cfg5_maxrate_fired_per_sec": pr2.get(
                    "fullscale_cfg5_maxrate_fired_per_sec"
                ),
                "fullscale_cfg5_flip_lag_p99_ms": pr2.get(
                    "fullscale_cfg5_flip_lag_p99_ms"
                ),
            }
    except Exception as e:  # noqa: BLE001 — the sweep numbers still stand
        ref = {"error": f"{e.__class__.__name__}: {e}"}
    baseline = float(ref.get("fullscale_cfg5_maxrate_events_per_sec") or 1399.0)
    cap = sweep["rungs"]["adaptive"]["events_per_sec_sustained"]
    slo = sweep["rungs"]["adaptive-slo"]
    out = {
        "metric": (
            "full-scale (100k pods x 10k throttles) sustained ingest "
            "capacity, micro-batched pipeline (adaptive), burst-drain "
            "(pipeline apply + full reconcile drain)"
        ),
        "value": cap,
        "unit": "events/s",
        "platform": platform,
        "scale": [100_000, 10_000],
        "pr2_reference": ref,
        "capacity_x_pr2": round(cap / baseline, 2),
        "slo_window": {
            "events_per_sec_sustained": slo["events_per_sec_sustained"],
            "flip_lag_p99_ms": slo["flip_lag_p99_ms"],
            "flip_slo_ms": 150.0,
            "x_pr2": round(slo["events_per_sec_sustained"] / baseline, 2),
        },
        **sweep,
    }
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    path = f"BENCH_PR5_{platform.upper()}_{stamp}.json"
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    log(f"ingest sweep written to {path}")
    emit(out)


def build_sharded_stack(P, T, n_shards, groups=500, label="shards"):
    """The PR 9 multiprocess stack at scale: scatter-gather admission
    front in THIS process, ``n_shards`` worker processes (each a full
    vertical: store+index+device planes+controllers) spawned by the
    supervisor. Topology (incl. the flip band) is identical to
    build_served_stack so the rungs compare apples to apples; objects
    are seeded THROUGH the front in batches (the honest routing cost)."""
    import os as _os

    from kube_throttler_tpu.api.pod import Namespace, make_pod
    from kube_throttler_tpu.sharding.front import AdmissionFront
    from kube_throttler_tpu.sharding.supervisor import ShardSupervisor

    import random

    rng = random.Random(0)
    front = AdmissionFront(n_shards)
    supervisor = ShardSupervisor(
        front,
        use_device=True,
        env={**_os.environ, "KT_SHARD_QUIET": "1", "KT_LOCK_ASSERT": "0"},
    )
    t0 = time.perf_counter()
    supervisor.start(ready_timeout=600.0)
    log(f"[{label}] {n_shards} workers ready in {time.perf_counter()-t0:.1f}s")

    front.store.create_namespace(Namespace("default"))
    flip_mc = _flip_band_mc(P, groups)
    t0 = time.perf_counter()
    ops = [
        ("create", "Throttle", _served_throttle(i, groups, flip_band_mc=flip_mc))
        for i in range(T)
    ]
    for s in range(0, len(ops), 512):
        front.store.apply_events(ops[s : s + 512])
    t_thr = time.perf_counter() - t0
    log(f"[{label}] routed {T} throttles in {t_thr:.1f}s")

    from dataclasses import replace as _replace

    t0 = time.perf_counter()
    pod_ops = []
    for i in range(P):
        pod = make_pod(
            f"p{i}",
            labels={"grp": f"g{rng.randrange(groups)}"},
            requests={"cpu": f"{rng.randrange(1, 8) * 100}m"},
        )
        pod = _replace(pod, spec=_replace(pod.spec, node_name="node-1"))
        pod.status.phase = "Running"
        pod_ops.append(("create", "Pod", pod))
    for s in range(0, len(pod_ops), 1024):
        front.store.apply_events(pod_ops[s : s + 1024])
    t_pods = time.perf_counter() - t0
    log(f"[{label}] routed {P} pods in {t_pods:.1f}s "
        f"({t_pods/P*1e6:.0f}us/event through the routing index)")
    t0 = time.perf_counter()
    front.drain(timeout=900.0)
    log(f"[{label}] shards drained initial reconcile in "
        f"{time.perf_counter()-t0:.1f}s")
    stats = front.stats()
    spread = {
        sid: s.get("objects", {}) for sid, s in stats["shards"].items()
    }
    log(f"[{label}] keyspace spread: {spread}")
    return front, supervisor


def _sharded_drain(front, pipeline, timeout=600.0):
    if pipeline is not None:
        pipeline.flush(timeout=timeout)
    front.drain(timeout=timeout)
    time.sleep(0.5)  # status pushes ride their own flush cadence


def bench_shard_burst(front, label, n=30_000, repeats=2):
    """Aggregate burst-drain capacity through the sharded stack: N
    pre-built churn events (producer cost off the clock) through the
    front's micro-batch pipeline → routing → per-shard ingest → full
    reconcile drain on every shard. Applied-not-submitted accounting:
    the count is the front pipeline's events_applied (each a DISTINCT
    event, applied at its owning shards), the clock stops when every
    shard reports empty queues+workqueues."""
    import random
    from dataclasses import replace as _replace

    from kube_throttler_tpu.api.pod import make_pod
    from kube_throttler_tpu.engine.ingest import MicroBatchIngest
    from kube_throttler_tpu.resourcelist import pod_request_resource_list

    rng = random.Random(4)
    pods = front.store.list_pods()
    cur_cpu: dict = {}

    def _mk_ops():
        ops = []
        for _ in range(n):
            pod = pods[rng.randrange(len(pods))]
            prev = cur_cpu.get(pod.name)
            if prev is None:
                stored = pod_request_resource_list(pod).get("cpu")
                prev = int(stored * 1000) if stored else 0
            new_cpu = rng.randrange(1, 8) * 100
            if new_cpu == prev:
                new_cpu = new_cpu % 700 + 100
            cur_cpu[pod.name] = new_cpu
            updated = make_pod(
                pod.name, labels=pod.labels, requests={"cpu": f"{new_cpu}m"}
            )
            updated = _replace(updated, spec=_replace(updated.spec, node_name="node-1"))
            updated.status.phase = "Running"
            ops.append(("update", "Pod", updated))
        return ops

    runs = []
    for rep in range(max(1, int(repeats))):
        ops = _mk_ops()
        pipeline = MicroBatchIngest(front.store, batch_policy="adaptive", maxsize=n)
        t0 = time.perf_counter()
        pipeline.submit_many(ops)
        pipeline.flush(timeout=900.0)
        t_apply = time.perf_counter() - t0
        front.drain(timeout=900.0)
        t_total = time.perf_counter() - t0
        st = pipeline.stats()
        pipeline.stop()
        run = {
            "events": n,
            "apply_events_per_sec": round(n / t_apply),
            "events_per_sec_sustained": round(st["events_applied"] / t_total),
            "events_applied": st["events_applied"],
            "dropped": st["dropped"],
        }
        runs.append(run)
        log(
            f"[{label}] shard BURST (run {rep + 1}/{repeats}): {n} events, "
            f"front apply {run['apply_events_per_sec']:,}/s, fully "
            f"reconciled across shards in {t_total:.2f}s -> "
            f"{run['events_per_sec_sustained']:,} ev/s aggregate sustained"
        )
    result = dict(max(runs, key=lambda r: r["events_per_sec_sustained"]))
    result["runs"] = runs
    return result


def bench_shard_streaming(front, label, duration=8.0, pace_hz=0.0):
    """Paced churn through the sharded stack with crossing-anchored flip
    measurement ON THE FRONT STORE — the flip clock includes routing,
    IPC, the owning shard's two-lane reconcile, and the status push back
    to the front: the end-to-end publication latency an operator sees."""
    import random

    from kube_throttler_tpu.engine.ingest import MicroBatchIngest

    rng = random.Random(1)
    pending, flip_pending, pend_lock, lags, flip_lags, _fw, on_throttle_write = (
        _lag_tracker()
    )
    group_keys = _group_keys_of(front.store)
    flip_watch, run_sums = _flip_watch_of(front.store)
    front.store.add_event_handler("Throttle", on_throttle_write, replay=False)
    pipeline = MicroBatchIngest(front.store, max_batch=64, batch_policy="adaptive")
    try:
        n_events, t_fired, n_crossings = _drive_pod_churn(
            front.store, group_keys, pending, pend_lock, rng, duration, pace_hz,
            flip_state=(flip_watch, run_sums, flip_pending),
            apply=lambda pod: pipeline.submit("update", "Pod", pod),
        )
        t_start = time.perf_counter() - t_fired
        _sharded_drain(front, pipeline)
        t_total = time.perf_counter() - t_start
    finally:
        front.store.remove_event_handler("Throttle", on_throttle_write)
        ps = pipeline.stats()
        pipeline.stop()
    n_applied = ps["events_applied"]
    lag_arr = np.asarray(lags) if lags else np.asarray([0.0])
    flip_arr = np.asarray(flip_lags) if flip_lags else np.asarray([0.0])
    result = {
        "events_per_sec_sustained": round(n_applied / t_total),
        "events_per_sec_fired": round(n_events / t_fired),
        "events_applied": n_applied,
        "lag_p99_ms": round(float(np.percentile(lag_arr, 99)) * 1e3, 1),
        "flip_lag_p50_ms": round(float(np.percentile(flip_arr, 50)) * 1e3, 1),
        "flip_lag_p99_ms": round(float(np.percentile(flip_arr, 99)) * 1e3, 1),
        "flip_samples": len(flip_lags),
        "flip_crossings": n_crossings,
        "pace_hz": pace_hz,
    }
    mode = f"paced {pace_hz:,.0f}/s" if pace_hz else "max rate"
    log(
        f"[{label}] sharded churn ({mode}): "
        f"{result['events_per_sec_sustained']:,} ev/s sustained "
        f"({result['events_per_sec_fired']:,}/s fired); FLIP p50 "
        f"{result['flip_lag_p50_ms']}ms / p99 {result['flip_lag_p99_ms']}ms "
        f"over {result['flip_samples']} flips"
    )
    return result


def bench_shard_decisions(front, label, threads=4, duration=2.0, groups=500):
    """Served decisions/s through the scatter-gather front: concurrent
    callers fan out to the owning shards (one RPC per matching shard)
    and AND-merge. With selector-affinity sharding a probe touches ONE
    shard, so N front threads drive N workers concurrently — the
    multi-core decision path the GIL denies the single process."""
    import threading as _threading

    from kube_throttler_tpu.api.pod import make_pod

    probes = [
        make_pod(
            f"probe{i}",
            labels={"grp": f"g{i % groups}"},
            requests={"cpu": f"{(i % 7 + 1) * 100}m"},
        )
        for i in range(64)
    ]
    front.pre_filter(probes[0])  # warm the RPC path

    def measure(k):
        stop = _threading.Event()
        counts = [0] * k

        def worker(idx):
            j = idx
            while not stop.is_set():
                front.pre_filter(probes[j % len(probes)])
                counts[idx] += 1
                j += k

        ts = [_threading.Thread(target=worker, args=(w,)) for w in range(k)]
        for t in ts:
            t.start()
        time.sleep(duration)
        stop.set()
        for t in ts:
            t.join(timeout=10)
        return sum(counts) / duration

    rate1 = measure(1)
    rate_k = measure(threads)
    log(
        f"[{label}] scatter-gather decisions: {rate1:,.0f}/s x1 thread, "
        f"{rate_k:,.0f}/s x{threads} threads "
        f"(scaling {rate_k/max(rate1,1e-9):.2f}x)"
    )
    return {
        "decisions_per_sec_1thread": round(rate1),
        f"decisions_per_sec_{threads}threads": round(rate_k),
        "thread_scaling": round(rate_k / max(rate1, 1e-9), 2),
    }


def run_shard_sweep() -> None:
    """``python bench.py --shard-sweep``: the PR 9 acceptance artifact —
    aggregate ingest, served decisions, and flip p99 per worker count
    {1,2,4} at the PR 5 topology (100k pods × 10k throttles), written to
    BENCH_PR9_<platform>_<stamp>.json. The 3× acceptance target assumes
    ≥4 cores (one per worker + the front); ``host_cores`` is recorded so
    an under-provisioned run (this container has 1) reads as what it is:
    the protocol at full scale, not a parallel-speedup measurement."""
    import os as _os

    platform = "cpu"
    try:
        platform = jax.devices()[0].platform
    except Exception:
        pass
    host_cores = len(_os.sched_getaffinity(0))
    P, T = (100_000, 10_000)
    if "--quick" in sys.argv:
        P, T = (10_000, 1_000)
    shard_counts = [1, 2, 4]
    pr5_baseline = 3593.0
    # the PR 9 acceptance gate, detected at bench start: the ≥3× aggregate
    # target is ENFORCED (non-zero exit) only on a host with ≥5 cores (4
    # workers + the front each need one); an undersubscribed host records
    # the fact explicitly and keeps the gate advisory — the sweep then
    # measures protocol overhead, not parallel speedup
    required_cores = max(shard_counts) + 1
    gate_enforced = host_cores >= required_cores
    log(
        f"shard sweep: host_cores={host_cores} required={required_cores} → "
        f"3x gate {'ENFORCED' if gate_enforced else 'ADVISORY (undersubscribed)'}"
    )
    out = {
        "metric": (
            "aggregate full-scale sustained ingest / served decisions / "
            "flip p99 across shared-nothing worker processes "
            "(scatter-gather front, applied-not-submitted accounting)"
        ),
        "platform": platform,
        "host_cores": host_cores,
        "scale": [P, T],
        "pr5_single_core_events_per_sec": pr5_baseline,
        "shard_counts": {},
    }
    for n_shards in shard_counts:
        label = f"shards{n_shards}"
        front = supervisor = None
        try:
            front, supervisor = build_sharded_stack(P, T, n_shards, label=label)
            rung = {"workers": n_shards}
            rung["burst"] = bench_shard_burst(front, label)
            cap = rung["burst"]["events_per_sec_sustained"]
            # SLO ladder relative to measured capacity: fastest pace whose
            # flip p99 meets the 150ms SLO wins (every attempt recorded)
            attempts = []
            best = None
            for frac in (0.85, 0.7, 0.55):
                pace = max(500.0, cap * frac)
                att = bench_shard_streaming(
                    front, f"{label}@{pace:.0f}", duration=10.0, pace_hz=pace
                )
                attempts.append(att)
                if att["flip_lag_p99_ms"] <= 150.0 and (
                    best is None
                    or att["events_per_sec_sustained"]
                    > best["events_per_sec_sustained"]
                ):
                    best = att
            if best is None:
                best = min(attempts, key=lambda a: a["flip_lag_p99_ms"])
            rung["slo_window"] = best
            rung["slo_attempts"] = attempts
            rung["decisions"] = bench_shard_decisions(front, label)
            stats = front.stats()
            rung["per_shard_applied"] = {
                sid: s.get("ingest", {}).get("events_applied")
                for sid, s in stats["shards"].items()
            }
            rung["route_misses"] = stats["route_misses"]
            out["shard_counts"][str(n_shards)] = rung
        except Exception as e:  # noqa: BLE001 — record and continue the sweep
            log(f"[{label}] FAILED: {e.__class__.__name__}: {e}")
            log(traceback.format_exc(limit=6))
            out["shard_counts"][str(n_shards)] = {
                "workers": n_shards,
                "error": f"{e.__class__.__name__}: {str(e)[:300]}",
            }
        finally:
            if supervisor is not None:
                supervisor.stop()
            if front is not None:
                front.stop()
    best4 = (
        out["shard_counts"].get("4", {}).get("burst", {}).get(
            "events_per_sec_sustained"
        )
    )
    if best4:
        out["aggregate_x_pr5"] = round(best4 / pr5_baseline, 2)
        out["meets_3x"] = bool(best4 >= 3 * pr5_baseline)
    out["undersubscribed"] = host_cores < required_cores
    out["gate_3x"] = {
        "required_cores": required_cores,
        "host_cores": host_cores,
        "enforced": gate_enforced,
        "meets_3x": out.get("meets_3x"),
        "advisory": (
            None
            if gate_enforced
            else (
                f"host exposes {host_cores} core(s) < {required_cores}: "
                f"{max(shard_counts)} workers + the front timeshare, so the "
                "sweep measures sharding-protocol overhead, not parallel "
                "speedup — rerun on a ≥5-core host to enforce the ≥3× "
                f"aggregate target vs PR 5's {pr5_baseline:,.0f} ev/s"
            )
        ),
    }
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    path = f"BENCH_PR9_{platform.upper()}_{stamp}.json"
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    log(f"shard sweep written to {path}")
    emit(out)
    if gate_enforced and not out.get("meets_3x"):
        log(
            f"shard sweep FAILED the enforced 3x gate: aggregate "
            f"{best4 or 0:,.0f} ev/s < {3 * pr5_baseline:,.0f}"
        )
        raise SystemExit(1)


def run_gang_bench() -> None:
    """``python bench.py --gang``: the gang-admission rung — bursty
    all-or-nothing group arrivals (mixed sizes 2/4/8/16) against ONE hot
    throttle, with the cfg5 paced pod churn running through the
    controllers underneath. Reports the all-or-nothing admit rate (and
    asserts ZERO partial admissions observable in the ledger), group
    admission latency percentiles (batched feasibility dispatch + atomic
    group reserve), and the per-pod flip p99 of the concurrent churn
    window — the PR 5 SLO (≤150 ms) must hold with gangs in the mix.
    ``--full`` runs the 100k×10k shape; default is the 10k×1k rung."""
    import random
    import threading as _threading
    from collections import deque

    from kube_throttler_tpu.api.pod import make_pod
    from kube_throttler_tpu.api.types import (
        LabelSelector,
        ResourceAmount,
        Throttle,
        ThrottleSelector,
        ThrottleSelectorTerm,
        ThrottleSpec,
    )

    platform = "cpu"
    try:
        platform = jax.devices()[0].platform
    except Exception:
        pass
    full = "--full" in sys.argv
    P, T = (100_000, 10_000) if full else (10_000, 1_000)
    groups = 500
    store, plugin = build_served_stack(P, T, groups, label="gang")

    # the HOT throttle every gang lands on: a cpu budget of 16 admits 32
    # 500m ranks — bursts of mixed sizes oversubscribe it, so admit/reject
    # both happen and capacity cycles as held groups roll back
    hot = Throttle(
        name="gang-hot",
        spec=ThrottleSpec(
            throttler_name="kube-throttler",
            threshold=ResourceAmount.of(requests={"cpu": "16"}),
            selector=ThrottleSelector(
                selector_terms=(
                    ThrottleSelectorTerm(
                        LabelSelector(match_labels={"grp": "gang-hot"})
                    ),
                )
            ),
        ),
    )
    store.create_throttle(hot)

    # prewarm the gang kernel's shape rungs (member pads 8 and 16 cover
    # sizes 2/4/8/16): the first dispatch's XLA compile (~2s on CPU) must
    # not land inside the measured admission window
    for warm_n in (2, 16):
        warm = [
            make_pod(
                f"gangwarm{warm_n}-r{i}",
                labels={"grp": "gang-hot"},
                requests={"cpu": "500m"},
                group=f"gangwarm{warm_n}",
                group_size=warm_n,
            )
            for i in range(warm_n)
        ]
        plugin.pre_filter_gang(f"default/gangwarm{warm_n}", warm)

    stop = _threading.Event()
    gang_stats = {
        "admit_lat": [],
        "check_lat": [],
        "admitted": 0,
        "rejected": 0,
        "violations": 0,
        "sizes": {},
    }

    def gang_driver() -> None:
        rng = random.Random(7)
        held: deque = deque()  # (release_time, group_key)
        gid = 0
        sizes = (2, 4, 8, 16)
        cache = plugin.throttle_ctr.cache
        while not stop.is_set():
            now = time.perf_counter()
            while held and held[0][0] <= now:
                _, gk = held.popleft()
                plugin.unreserve_gang(gk)
            for _ in range(rng.randint(1, 4)):  # one bursty arrival wave
                gid += 1
                size = rng.choice(sizes)
                gk = f"default/gang{gid}"
                members = [
                    make_pod(
                        f"gang{gid}-r{i}",
                        labels={"grp": "gang-hot"},
                        requests={"cpu": "500m"},
                        group=f"gang{gid}",
                        group_size=size,
                    )
                    for i in range(size)
                ]
                t0 = time.perf_counter()
                st = plugin.pre_filter_gang(gk, members)
                t1 = time.perf_counter()
                ok = st.is_success() and plugin.reserve_gang(gk, members).is_success()
                t2 = time.perf_counter()
                gang_stats["check_lat"].append(t1 - t0)
                gang_stats["admit_lat"].append(t2 - t0)
                gang_stats["sizes"][size] = gang_stats["sizes"].get(size, 0) + 1
                # all-or-nothing witness straight from the ledger: every
                # member reserved on the hot key, or none of them
                reserved = cache.reserved_pod_keys(hot.key)
                member_keys = {m.key for m in members}
                n_in = len(member_keys & reserved)
                if ok:
                    gang_stats["admitted"] += 1
                    if n_in != size:
                        gang_stats["violations"] += 1
                    held.append((time.perf_counter() + 0.05, gk))
                else:
                    gang_stats["rejected"] += 1
                    if n_in != 0:
                        gang_stats["violations"] += 1
            stop.wait(0.05)
        while held:
            plugin.unreserve_gang(held.popleft()[1])

    driver = _threading.Thread(target=gang_driver, daemon=True)
    driver.start()
    try:
        streaming = bench_served_streaming(
            store, plugin, "gang-churn", groups=groups,
            duration=4.0 if not full else 8.0, pace_hz=1000.0,
            ingest_batch="adaptive",
        )
    finally:
        stop.set()
        driver.join(timeout=10)
        plugin.stop()

    lat = np.asarray(gang_stats["admit_lat"]) if gang_stats["admit_lat"] else np.asarray([0.0])
    chk = np.asarray(gang_stats["check_lat"]) if gang_stats["check_lat"] else np.asarray([0.0])
    total = gang_stats["admitted"] + gang_stats["rejected"]
    out = {
        "metric": (
            "gang admission p99 (batched group feasibility + atomic "
            "all-or-nothing reserve) under bursty mixed-size arrivals on "
            "one hot throttle, cfg5 churn paced 1k ev/s underneath"
        ),
        "value": round(float(np.percentile(lat, 99)) * 1e3, 3),
        "unit": "ms",
        "platform": platform,
        "scale": [P, T],
        "gang_groups_total": total,
        "gang_groups_admitted": gang_stats["admitted"],
        "gang_groups_rejected": gang_stats["rejected"],
        "gang_admit_rate": round(gang_stats["admitted"] / max(total, 1), 3),
        "gang_all_or_nothing_violations": gang_stats["violations"],
        "gang_sizes": gang_stats["sizes"],
        "gang_admission_p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
        "gang_admission_p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
        "gang_check_p50_ms": round(float(np.percentile(chk, 50)) * 1e3, 3),
        "gang_check_p99_ms": round(float(np.percentile(chk, 99)) * 1e3, 3),
        "churn_flip_lag_p99_ms": streaming["flip_lag_p99_ms"],
        "churn_flip_samples": streaming["flip_samples"],
        "flip_slo_ms": 150.0,
        "flip_slo_met": bool(
            streaming["flip_samples"] == 0
            or streaming["flip_lag_p99_ms"] <= 150.0
        ),
        "churn": streaming,
    }
    log(
        f"[gang] {total} groups ({gang_stats['admitted']} admitted / "
        f"{gang_stats['rejected']} rejected, admit rate "
        f"{out['gang_admit_rate']:.0%}), admission p50 "
        f"{out['gang_admission_p50_ms']:.2f}ms / p99 "
        f"{out['gang_admission_p99_ms']:.2f}ms, all-or-nothing violations "
        f"{gang_stats['violations']}; churn flip p99 "
        f"{streaming['flip_lag_p99_ms']:.1f}ms over "
        f"{streaming['flip_samples']} flips (SLO ≤150ms: "
        f"{'MET' if out['flip_slo_met'] else 'MISSED'})"
    )
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    path = f"BENCH_GANG_{platform.upper()}_{stamp}.json"
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    log(f"gang rung written to {path}")
    emit(out)


def run_decision_sweep() -> None:
    """``python bench.py --decision-sweep``: the PR 17 acceptance artifact —
    the interned-verdict cache against the uncached reference on the REAL
    served stack. Rungs: uncached / cold / warm at 1 and 4 threads, on a
    DEGENERATE probe mix (few request shapes — the autoscaler-storm case
    the cache exists for) and a DIVERSE mix (every probe a distinct
    shape — the cache's worst case, where it must not regress the path).
    Then epoch-churn sensitivity: warm throughput + hit rate while a
    background mutator edits throttle thresholds at {0,10,100} Hz, and an
    oracle sweep interleaving mutations with cache-vs-recompute verdict
    comparisons. Gates (enforced, non-zero exit): warm degenerate ≥10×
    the uncached reference single-threaded, and ZERO wrong verdicts vs
    the oracle. ``--full`` runs 100k×10k; default is the 10k×1k rung."""
    import random
    import threading as _threading
    from dataclasses import replace as _replace

    from kube_throttler_tpu.api.pod import make_pod
    from kube_throttler_tpu.api.types import ResourceAmount

    platform = "cpu"
    try:
        platform = jax.devices()[0].platform
    except Exception:
        pass
    full = "--full" in sys.argv
    P, T = (100_000, 10_000) if full else (10_000, 1_000)
    groups = 500
    store, plugin = build_served_stack(P, T, groups, label="decisions")
    cache = plugin.verdict_cache
    if cache is None:
        log("decision sweep FAILED: plugin built without a verdict cache "
            "(KT_VERDICT_CACHE=0 or no device manager)")
        raise SystemExit(1)

    # DEGENERATE mix: 64 probe objects over 8 (grp, cpu) shapes — after one
    # pass every further decision is a pure hash probe. DIVERSE mix: 2000
    # probes each with a distinct (grp, cpu) pair, so the cache's first
    # pass is all misses and steady state still hits (2000 < capacity).
    degenerate = [
        make_pod(
            f"deg{i}",
            labels={"grp": f"g{i % 4}"},
            requests={"cpu": f"{((i // 4) % 2 + 1) * 100}m"},
        )
        for i in range(64)
    ]
    diverse = [
        make_pod(
            f"div{i}",
            labels={"grp": f"g{i % groups}"},
            requests={"cpu": f"{(i % 97 + 1) * 10}m"},
        )
        for i in range(2000)
    ]

    def _measure_once(probes, threads=1, duration=2.0):
        """Drive pre_filter over `probes` round-robin for `duration`;
        returns (decisions_per_sec, hit_rate) from cache stat deltas."""
        h0, m0 = cache.stats()[:2]
        stop = _threading.Event()
        counts = [0] * threads

        def worker(idx):
            j = idx
            n = len(probes)
            while not stop.is_set():
                plugin.pre_filter(probes[j % n])
                counts[idx] += 1
                j += threads

        ths = [_threading.Thread(target=worker, args=(w,)) for w in range(threads)]
        for th in ths:
            th.start()
        time.sleep(duration)
        stop.set()
        for th in ths:
            th.join(timeout=10)
        h1, m1 = cache.stats()[:2]
        dh, dm = h1 - h0, m1 - m0
        hit_rate = dh / max(dh + dm, 1)
        return sum(counts) / duration, hit_rate

    def measure(probes, threads=1, duration=1.5, reps=3):
        """Median of `reps` interleaved passes (same protocol as
        bench_served_prefilter): a single-core host's co-tenant noise
        moves one 2s window by ±30%, which would make the 10x gate flap."""
        runs = [_measure_once(probes, threads, duration) for _ in range(reps)]
        rates = sorted(r for r, _ in runs)
        hits = sorted(h for _, h in runs)
        return rates[len(rates) // 2], hits[len(hits) // 2]

    def measure_uncached(probes, threads=1, duration=1.5, reps=3):
        """The reference: same drive with the cache detached — every
        decision walks the full plane path. Median of `reps` passes."""
        saved, plugin.verdict_cache = plugin.verdict_cache, None
        try:
            rates = []
            for _rep in range(reps):
                stop = _threading.Event()
                counts = [0] * threads

                def worker(idx):
                    j = idx
                    n = len(probes)
                    while not stop.is_set():
                        plugin.pre_filter(probes[j % n])
                        counts[idx] += 1
                        j += threads

                ths = [
                    _threading.Thread(target=worker, args=(w,))
                    for w in range(threads)
                ]
                for th in ths:
                    th.start()
                time.sleep(duration)
                stop.set()
                for th in ths:
                    th.join(timeout=10)
                rates.append(sum(counts) / duration)
                time.sleep(0.05)
            return sorted(rates)[len(rates) // 2]
        finally:
            plugin.verdict_cache = saved

    def cold_pass(probes):
        """First-touch rate: fresh cache, ONE pass over the probe set —
        every decision is a miss + validate-after-compute insert."""
        cache.invalidate_all()
        t0 = time.perf_counter()
        for p in probes:
            plugin.pre_filter(p)
        dt = time.perf_counter() - t0
        return len(probes) / dt

    out: dict = {
        "metric": (
            "served decisions/s: interned-verdict cache vs uncached "
            "reference (degenerate + diverse probe mixes, real daemon stack)"
        ),
        "platform": platform,
        "host_cpus": os.cpu_count(),
        "scale": [P, T],
        "cache_capacity": cache.capacity,
        "mixes": {},
    }

    for name, probes in (("degenerate", degenerate), ("diverse", diverse)):
        rung: dict = {"probes": len(probes),
                      "shapes": 8 if name == "degenerate" else len(probes)}
        rung["uncached_1t"] = measure_uncached(probes, threads=1)
        rung["cold_pass"] = cold_pass(probes)
        # warm the cache fully before the steady-state rungs
        for p in probes:
            plugin.pre_filter(p)
        r1, hr1 = measure(probes, threads=1)
        r4, hr4 = measure(probes, threads=4)
        rung["warm_1t"], rung["warm_1t_hit_rate"] = r1, round(hr1, 4)
        rung["warm_4t"], rung["warm_4t_hit_rate"] = r4, round(hr4, 4)
        rung["speedup_warm_vs_uncached_1t"] = round(r1 / max(rung["uncached_1t"], 1e-9), 2)
        log(
            f"[decisions:{name}] uncached {rung['uncached_1t']:,.0f}/s, "
            f"cold {rung['cold_pass']:,.0f}/s, warm {r1:,.0f}/s x1 "
            f"(hit {hr1:.1%}) / {r4:,.0f}/s x4 (hit {hr4:.1%}) — "
            f"{rung['speedup_warm_vs_uncached_1t']}x warm vs uncached"
        )
        out["mixes"][name] = rung

    # ---- epoch-churn sensitivity: a mutator edits flip-band throttle
    # thresholds at a fixed pace while the degenerate warm rung runs. Each
    # edit bumps the touched cols' epochs, so every covered entry goes
    # stale and the next probe recomputes — hit rate degrades with pace
    # but throughput must degrade gracefully, not collapse.
    # mutate the throttles that SELECT the degenerate groups (t{i} selects
    # g{i%groups}) so every edit actually covers served entries
    churn_keys = [f"default/t{i}" for i in range(4)]

    def churn_rung(pace_hz: float, duration=2.0):
        stop = _threading.Event()
        edits = [0]

        def mutator():
            # the bench plugin runs workerless (build_served_stack drives
            # reconciles explicitly), so each edit is followed by the
            # reconcile that publishes it to the planes — that reconcile
            # is what bumps the covered cols' epochs
            rng = random.Random(17)
            period = 1.0 / pace_hz
            while not stop.is_set():
                key = churn_keys[edits[0] % len(churn_keys)]
                ns, nm = key.split("/")
                thr = store.get_throttle(ns, nm)
                mc = rng.randrange(1, 200) * 100
                store.update_throttle_spec(
                    _replace(
                        thr,
                        spec=_replace(
                            thr.spec,
                            threshold=ResourceAmount.of(requests={"cpu": f"{mc}m"}),
                        ),
                    )
                )
                plugin.run_pending_once()
                edits[0] += 1
                time.sleep(period)

        th = None
        if pace_hz > 0:
            th = _threading.Thread(target=mutator)
            th.start()
        rate, hit = measure(degenerate, threads=1, duration=duration)
        stop.set()
        if th is not None:
            th.join(timeout=10)
        return {"pace_hz": pace_hz, "decisions_per_sec": rate,
                "hit_rate": round(hit, 4), "edits": edits[0]}

    out["epoch_churn"] = [churn_rung(hz) for hz in (0.0, 10.0, 100.0)]
    for r in out["epoch_churn"]:
        log(
            f"[decisions:churn@{r['pace_hz']:.0f}Hz] "
            f"{r['decisions_per_sec']:,.0f}/s, hit {r['hit_rate']:.1%} "
            f"({r['edits']} threshold edits)"
        )

    # ---- oracle sweep: interleave mutations with cache-vs-recompute
    # comparisons. After each mutation the pending reconciles are drained
    # (the workerless bench plugin reconciles on demand), then every
    # probe's CACHED verdict must match a fresh recompute — code and
    # reason set both. Any divergence is a stale cache entry the epoch
    # discipline failed to kill.
    def settle():
        while plugin.run_pending_once():
            pass

    rng = random.Random(29)
    wrong = 0
    compared = 0
    oracle_probes = degenerate + diverse[:200]
    for round_i in range(30):
        key = churn_keys[rng.randrange(len(churn_keys))]
        ns, nm = key.split("/")
        thr = store.get_throttle(ns, nm)
        mc = rng.randrange(1, 200) * 100
        store.update_throttle_spec(
            _replace(
                thr,
                spec=_replace(
                    thr.spec,
                    threshold=ResourceAmount.of(requests={"cpu": f"{mc}m"}),
                ),
            )
        )
        settle()
        for p in rng.sample(oracle_probes, 24):
            got = plugin.pre_filter(p)
            want = plugin._pre_filter_uncached(p, emit_events=False)
            compared += 1
            if (got.code, tuple(sorted(got.reasons))) != (
                    want.code, tuple(sorted(want.reasons))):
                wrong += 1
                log(f"[decisions:oracle] WRONG verdict for {p.name}: "
                    f"cached {got.code}/{got.reasons} vs "
                    f"oracle {want.code}/{want.reasons}")
    hits, misses, entries, invalidations, insertions = cache.stats()
    out["oracle"] = {"compared": compared, "wrong": wrong, "rounds": 30}
    out["cache_stats"] = {
        "hits": hits, "misses": misses, "entries": entries,
        "invalidations": invalidations, "insertions": insertions,
    }
    log(f"[decisions:oracle] {compared} comparisons under churn, {wrong} wrong")

    speedup = out["mixes"]["degenerate"]["speedup_warm_vs_uncached_1t"]
    out["gate_10x"] = {
        "speedup_warm_vs_uncached_1t": speedup,
        "meets_10x": bool(speedup >= 10.0),
        "wrong_verdicts": wrong,
    }
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    path = f"BENCH_PR17_{platform.upper()}_{stamp}.json"
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    log(f"decision sweep written to {path}")
    emit(out)
    if not out["gate_10x"]["meets_10x"] or wrong:
        log(
            f"decision sweep FAILED its gate: speedup {speedup}x "
            f"(need ≥10x), wrong verdicts {wrong} (need 0)"
        )
        raise SystemExit(1)


def run_route_sweep() -> None:
    """``python bench.py --route-sweep``: the PR 20 acceptance artifact —
    front-side event transport cost per event, the pickle-socketpair
    baseline against the zero-copy shm ring (sharding/shmring.py), under
    a BURST arrival shape (batch=256 — the informer-resync case) and a
    SUSTAINED shape (batch=8 — steady churn trickle). Both lanes are
    measured sender-side with a drainer on the other end, which is what
    the ≤20 µs/event routing target bounds: the worker's decode runs on
    the worker's core, not the front's. A third rung drives the REAL
    2-shard multiprocess fleet end-to-end (seed + churn + drain) with
    the ring on and off for a wall-clock sanity delta. Gates (burst
    rung): shm ≤20 µs/event AND ≥3.5x the pickle baseline — enforced on
    hosts with at least KT_SCENARIO_LATENCY_CORE_FLOOR cores (default
    2, scenarios/slo.py), advisory (reported, exit 0) below it, exactly
    like the scenario latency SLOs."""
    import socket
    import threading as _threading

    from kube_throttler_tpu.api.pod import make_pod
    from kube_throttler_tpu.sharding import ipc as _ipc
    from kube_throttler_tpu.sharding.shmring import (
        ShmEventLane,
        ShmRingReader,
        ShmRingWriter,
        shm_available,
    )

    platform = "cpu"
    try:
        platform = jax.devices()[0].platform
    except Exception:
        pass
    if not shm_available():
        log("route sweep FAILED: multiprocessing.shared_memory unavailable")
        raise SystemExit(1)

    # realistic routed-op mix: mostly Pod upserts (the hot class — every
    # pod create/update/phase flip fans out), a delete tail, distinct
    # label/request shapes across a few hundred pods so the shm string
    # table sees steady-state interning, not a degenerate single shape.
    # The pods go THROUGH a real Store first: the front routes arena-
    # absorbed objects (canonical shared label dicts + stamped request
    # shape ids), and both lanes get the same objects — pickle just
    # cannot exploit the stamps
    from kube_throttler_tpu.api.pod import Namespace
    from kube_throttler_tpu.engine.store import Store

    store = Store()
    store.create_namespace(Namespace("default"))
    pods = []
    for i in range(512):
        p = make_pod(
            f"bp{i}",
            labels={"grp": f"g{i % 32}", "tier": f"t{i % 5}"},
            requests={"cpu": f"{(i % 15 + 1) * 100}m", "memory": f"{(i % 7 + 1)}Gi"},
            node_name=f"node-{i % 16}",
            phase="Running",
        )
        store.create_pod(p)
        pods.append(p)
    ops = []
    for i, p in enumerate(pods):
        ops.append(("upsert", "Pod", p))
        if i % 8 == 7:
            ops.append(("delete", "Pod", f"default/bp{i - 7}"))

    def bench_pickle(batch: int, duration: float) -> float:
        """µs/event for send_frame(encode_evt_batch(...)) over a drained
        socketpair — exactly the ShardClient._send_loop fallback path."""
        a, b = socket.socketpair()
        stop = _threading.Event()

        def drain() -> None:
            try:
                while b.recv(1 << 16):
                    pass
            except OSError:
                pass

        th = _threading.Thread(target=drain, daemon=True)
        th.start()
        lock = _threading.Lock()
        sent, j, n = 0, 0, len(ops)
        t0 = time.perf_counter()
        try:
            while time.perf_counter() - t0 < duration:
                chunk = [ops[(j + k) % n] for k in range(batch)]
                j += batch
                _ipc.send_frame(
                    a, lock, "evt", 0, _ipc.encode_evt_batch(chunk), epoch=1
                )
                sent += batch
        finally:
            elapsed = time.perf_counter() - t0
            a.close()
            b.close()
            stop.set()
            th.join(timeout=5)
        return elapsed / sent * 1e6

    shm_seq = [0]

    def bench_shm(batch: int, duration: float) -> float:
        """µs/event for ShmEventLane.send (FrameEncoder + ring commit +
        doorbell) with an advancing reader on the other end."""
        shm_seq[0] += 1
        writer = ShmRingWriter(
            f"kt_bench_{os.getpid()}_{shm_seq[0]}",
            slots=4096,
            arena_bytes=32 << 20,
        )
        reader = ShmRingReader(writer.name)
        lane = ShmEventLane(writer)
        stop = _threading.Event()

        def drain() -> None:
            while not stop.is_set():
                view = reader.peek(timeout=0.05)
                if view is not None:
                    del view
                    reader.advance()

        th = _threading.Thread(target=drain, daemon=True)
        th.start()
        sent, j, n = 0, 0, len(ops)
        t0 = time.perf_counter()
        try:
            while time.perf_counter() - t0 < duration:
                chunk = [ops[(j + k) % n] for k in range(batch)]
                j += batch
                if not lane.send(chunk, epoch=1):
                    raise RuntimeError("bench ring died")
                sent += batch
        finally:
            elapsed = time.perf_counter() - t0
            stop.set()
            th.join(timeout=5)
            reader.close()
            lane.close()
        return elapsed / sent * 1e6

    def median3(fn, *a):
        return sorted(fn(*a) for _ in range(3))[1]

    duration = 2.0 if "--full" in sys.argv else 1.0
    out: dict = {"bench": "route_sweep", "platform": platform, "shapes": {}}
    for shape, batch in (("burst", 256), ("sustained", 8)):
        pk = median3(bench_pickle, batch, duration)
        sm = median3(bench_shm, batch, duration)
        out["shapes"][shape] = {
            "batch": batch,
            "pickle_us_per_event": round(pk, 3),
            "shm_us_per_event": round(sm, 3),
            "speedup": round(pk / sm, 2),
        }
        log(f"[route:{shape}] batch={batch} pickle={pk:.1f}us "
            f"shm={sm:.1f}us speedup={pk / sm:.2f}x")

    # end-to-end sanity rung: the real 2-shard fleet, ring on vs off.
    # Wall-clock here is dominated by worker recompute, not transport —
    # recorded for the artifact, never gated.
    def fleet_run(shm_on: bool) -> float:
        from kube_throttler_tpu.sharding.front import AdmissionFront
        from kube_throttler_tpu.sharding.supervisor import ShardSupervisor

        import tools.harness as H
        from kube_throttler_tpu.api.pod import Namespace

        env = {
            **os.environ,
            "KT_SHARD_QUIET": "1",
            "KT_LOCK_ASSERT": "0",
            "KT_SHM_RING": "1" if shm_on else "0",
        }
        os.environ["KT_SHM_RING"] = env["KT_SHM_RING"]
        front = AdmissionFront(2)
        sup = ShardSupervisor(front, use_device=False, env=env)
        try:
            sup.start(ready_timeout=180.0)
            t0 = time.perf_counter()
            front.store.create_namespace(Namespace("default"))
            for i in range(8):
                front.store.create_throttle(H.make_throttle(i))
            for i in range(400):
                front.store.create_pod(
                    make_pod(
                        f"fp{i}",
                        labels={"grp": f"g{i % 8}"},
                        requests={"cpu": f"{(i % 9 + 1) * 100}m"},
                        node_name="node-1",
                        phase="Running",
                    )
                )
            if not front.drain(timeout=120.0):
                raise RuntimeError("fleet drain timed out")
            return time.perf_counter() - t0
        finally:
            sup.stop()
            front.stop()
            os.environ.pop("KT_SHM_RING", None)

    try:
        out["fleet_end_to_end"] = {
            "pods": 400,
            "shm_seconds": round(fleet_run(True), 3),
            "pickle_seconds": round(fleet_run(False), 3),
        }
        log(f"[route:fleet] {out['fleet_end_to_end']}")
    except Exception as e:  # noqa: BLE001 — sanity rung, never gates
        out["fleet_end_to_end"] = {"error": f"{e.__class__.__name__}: {e}"}
        log(f"[route:fleet] skipped: {out['fleet_end_to_end']['error']}")

    from kube_throttler_tpu.scenarios.slo import _latency_gates_enforced

    burst = out["shapes"]["burst"]
    meets = burst["shm_us_per_event"] <= 20.0 and burst["speedup"] >= 3.5
    enforced = _latency_gates_enforced()
    out["gate"] = {
        "shm_us_per_event": burst["shm_us_per_event"],
        "bound_us": 20.0,
        "speedup": burst["speedup"],
        "bound_speedup": 3.5,
        "meets": bool(meets),
        "enforced": bool(enforced),
    }
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    path = f"BENCH_PR20_{platform.upper()}_{stamp}.json"
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    log(f"route sweep written to {path}")
    emit(out)
    if not meets:
        msg = (
            f"route sweep gate: shm {burst['shm_us_per_event']}us/event "
            f"(need <=20), speedup {burst['speedup']}x (need >=3.5)"
        )
        if enforced:
            log(f"route sweep FAILED its gate: {msg}")
            raise SystemExit(1)
        log(f"route sweep ADVISORY (below core floor): {msg}")


def bench_remote_pipeline(label, P=10000, T=1000, groups=500, duration=6.0, pace_hz=1000.0):
    """cfg5 through the WIRE: pod churn lands on a (mock) apiserver, flows
    over real HTTP list+watch into the reflector-fed local cache, the
    controllers reconcile, and the status PUTs land back on the remote
    status subresource — the full remote-mode daemon loop
    (plugin.go:71-130 + throttle_controller.go:170 UpdateStatus). Lag is
    measured remote-commit to remote-commit: from the pod event at the
    apiserver to the throttle status write arriving back there. Rate
    limiting is disabled (qps=None) so this measures pipeline capacity,
    not the token bucket (the reference's client-go default of 50 QPS
    would bind ~50 writes/s)."""
    import random
    from dataclasses import replace as _replace

    from kube_throttler_tpu.api.pod import Namespace, make_pod
    from kube_throttler_tpu.client.mockserver import MockApiServer
    from kube_throttler_tpu.client.transport import RemoteSession, RestConfig
    from kube_throttler_tpu.engine.store import Store
    from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args

    rng = random.Random(0)
    server = MockApiServer(bookmark_interval=1.0)
    remote = server.store
    remote.create_namespace(Namespace("default"))
    flip_mc = _flip_band_mc(P, groups)
    for i in range(T):
        remote.create_throttle(_served_throttle(i, groups, flip_band_mc=flip_mc))
    for i in range(P):
        pod = make_pod(
            f"p{i}",
            labels={"grp": f"g{rng.randrange(groups)}"},
            requests={"cpu": f"{rng.randrange(1, 8) * 100}m"},
        )
        pod = _replace(pod, spec=_replace(pod.spec, node_name="node-1"))
        pod.status.phase = "Running"
        remote.create_pod(pod)
    server.start()

    local = Store()
    from kube_throttler_tpu.metrics import Registry as _Registry

    session_registry = _Registry()
    session = RemoteSession(
        RestConfig(server=server.url), local, metrics_registry=session_registry, qps=None
    )
    plugin = None
    wire_rtt_ms = 0.0
    commit_counts: dict = {}
    # lag is remote-commit→remote-commit: the tracker watches the REMOTE
    # store's Throttle MODIFIEDs (the arriving status PUTs)
    pending, flip_pending, pend_lock, lags, flip_lags, _flip_walls, on_remote_status = (
        _lag_tracker()
    )
    group_keys = _group_keys_of(remote)
    flip_watch, run_sums = _flip_watch_of(remote)
    try:
        session.start(sync_timeout=30)
        plugin = KubeThrottler(
            decode_plugin_args(
                {"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}
            ),
            local,
            use_device=True,
            start_workers=True,
            # the async committer (what the daemon wires in production):
            # batch submit + newest-wins coalescing + N concurrent PUT
            # workers over keep-alive connections
            status_writer=session.status_committer,
        )
        # initial statuses converge before the measured window (every group
        # has pods, so every throttle ends with a materialized used count)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if all(
                t.status.used.resource_counts is not None
                for t in remote.list_throttles()
            ):
                break
            time.sleep(0.25)
        # pre-serving GC posture, same as the daemon (cli.py /
        # build_served_stack): freeze the converged heap so full-GC pauses
        # don't land in the measured window
        from kube_throttler_tpu.utils.gchygiene import freeze_startup_heap

        freeze_startup_heap()
        # raw wire capacity probe: one warm status PUT round trip, repeated
        # — the per-request floor every commit pays (http.client +
        # http.server protocol overhead shares the same core as the whole
        # pipeline on this host, so it bounds achievable PUTs/s)
        probe_thrs = remote.list_throttles()
        if probe_thrs:
            done = 0
            t0 = time.perf_counter()
            for _ in range(30):
                try:
                    session.status_writer.update_throttle_status(probe_thrs[0])
                    done += 1
                except Exception:
                    # 409 against a committer PUT still in flight for this
                    # key is possible; a lost probe must not lose the bench
                    pass
            if done:
                wire_rtt_ms = (time.perf_counter() - t0) / done * 1e3
        remote.add_event_handler("Throttle", on_remote_status, replay=False)
        n_events, t_fired, n_crossings = _drive_pod_churn(
            remote, group_keys, pending, pend_lock, rng, duration, pace_hz,
            flip_state=(flip_watch, run_sums, flip_pending),
        )
        # drain tail: give in-flight writes a bounded window to land
        session.status_committer.flush(timeout=min(3.0, duration / 2))
        time.sleep(0.3)
        commit_counter = session_registry.counter_vec(
            "kube_throttler_remote_status_commit_total", "", ["kind", "result"]
        )
        for (kind, result), v in commit_counter.collect().items():
            commit_counts[f"{kind}:{result}"] = int(v)
    finally:
        if plugin is not None:
            plugin.stop()
        session.stop()
        server.stop()

    # [0.0] sentinel when nothing landed (status_writes=0 disambiguates):
    # NaN would propagate into the one-line report and break strict JSON
    lag_arr = np.asarray(lags) if lags else np.asarray([0.0])
    flip_arr = np.asarray(flip_lags) if flip_lags else np.asarray([0.0])
    result = {
        "events_per_sec": n_events / t_fired,  # rate during the fire window
        "lag_p50_ms": float(np.percentile(lag_arr, 50)) * 1e3,
        "lag_p99_ms": float(np.percentile(lag_arr, 99)) * 1e3,
        "status_writes": len(lags),
        "flip_lag_p50_ms": float(np.percentile(flip_arr, 50)) * 1e3,
        "flip_lag_p99_ms": float(np.percentile(flip_arr, 99)) * 1e3,
        "flip_samples": len(flip_lags),
        "flip_crossings": n_crossings,
        "wire_put_rtt_ms": round(wire_rtt_ms, 3),
        "commit_counts": commit_counts,
    }
    log(
        f"[{label}] cfg5 REMOTE WIRE ({P} pods x {T} throttles, paced "
        f"{pace_hz:,.0f}/s): {n_events} events -> {result['events_per_sec']:,.0f}/s; "
        f"remote-commit lag p50 {result['lag_p50_ms']:.1f}ms / p99 "
        f"{result['lag_p99_ms']:.1f}ms over {len(lags)} status PUTs; FLIP "
        f"lag p50 {result['flip_lag_p50_ms']:.1f}ms / p99 "
        f"{result['flip_lag_p99_ms']:.1f}ms over {len(flip_lags)} flips "
        f"from {n_crossings} crossings (two-lane committer); raw wire PUT "
        f"RTT {wire_rtt_ms:.2f}ms (the "
        f"per-request protocol floor this host's single core pays "
        f"in-pipeline); committer outcomes {commit_counts} (watch -> "
        "reflector -> reconcile -> async committer -> HTTP status "
        "subresource)"
    )
    return result


def bench_example_scenario(label):
    """BASELINE config 1: the examples/throttle.yaml t1 + walkthrough pods
    through the FULL plugin stack on the host-oracle path (the 'CPU
    PreFilter reference scenario' — what the reference's Go hot path does
    per attempt, here per-decision host latency)."""
    import yaml

    from kube_throttler_tpu.api.pod import Namespace
    from kube_throttler_tpu.api.serialization import object_from_dict
    from kube_throttler_tpu.engine.store import Store
    from kube_throttler_tpu.plugin import KubeThrottler, decode_plugin_args

    store = Store()
    store.create_namespace(Namespace("default"))
    plugin = KubeThrottler(
        decode_plugin_args({"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}),
        store,
        use_device=False,
    )
    with open("examples/throttle.yaml") as f:
        store.create_throttle(object_from_dict(yaml.safe_load(f)))
    pods = []
    with open("examples/pods.yaml") as f:
        for doc in yaml.safe_load_all(f):
            pod = object_from_dict(doc)
            store.create_pod(pod)
            pods.append(pod)
    plugin.run_pending_once()

    i = [0]

    def one():
        plugin.pre_filter(pods[i[0] % len(pods)])
        i[0] += 1

    stats = host_percentiles(one, 2000)
    log(
        f"[{label}] example t1 + pods1-3, host-oracle PreFilter: "
        f"{stats['mean']*1e6:.1f}us mean / {stats['p99']*1e6:.1f}us p99 per decision "
        f"({1/stats['mean']:,.0f} decisions/sec)"
    )
    plugin.stop()
    return stats


def bench_selector_index(label, T=10_000, n_pods=200):
    """Host-side selector-mask maintenance (SURVEY hard part 3): per-pod-event
    row recompute against T compiled selector columns, native C++ vs Python."""
    import random

    from kube_throttler_tpu.api.pod import Namespace, make_pod
    from kube_throttler_tpu.api.types import (
        LabelSelector,
        ResourceAmount,
        Throttle,
        ThrottleSelector,
        ThrottleSelectorTerm,
        ThrottleSpec,
    )
    from kube_throttler_tpu.engine.index import SelectorIndex
    from kube_throttler_tpu.native import available

    rng = random.Random(0)
    throttles = [
        Throttle(
            name=f"t{i}",
            spec=ThrottleSpec(
                throttler_name="x",
                threshold=ResourceAmount.of(pod=1),
                selector=ThrottleSelector(
                    selector_terms=(
                        ThrottleSelectorTerm(
                            LabelSelector(match_labels={"grp": f"g{i % 500}"})
                        ),
                    )
                ),
            ),
        )
        for i in range(T)
    ]
    pods = [
        make_pod(f"p{i}", labels={"grp": f"g{rng.randrange(500)}"}) for i in range(n_pods)
    ]

    for use_native, name in ((True, "native C++"), (False, "python")):
        if use_native and not available():
            log(f"[{label}] native tier unavailable (no toolchain or KT_TPU_NO_NATIVE=1); python tier only")
            continue
        idx = SelectorIndex("throttle", pod_capacity=n_pods, throttle_capacity=T, use_native=use_native)
        idx.upsert_namespace(Namespace("default"))
        for thr in throttles:
            idx.upsert_throttle(thr)
        t0 = time.perf_counter()
        for pod in pods:
            idx.upsert_pod(pod)  # one mask-row recompute per pod event
        dt = (time.perf_counter() - t0) / n_pods
        log(f"[{label}] pod-event row recompute vs T={T} ({name}): {dt*1e6:.1f}us/event")


def _gc_pause_tracker():
    """Attach a gc callback recording collection pause durations; returns
    the mutable stats dict (max/count) and the callback (for removal)."""
    import gc

    state = {"max_s": 0.0, "count": 0, "_t0": None}

    def cb(phase, info):
        if phase == "start":
            state["_t0"] = time.perf_counter()
        elif state["_t0"] is not None:
            pause = time.perf_counter() - state["_t0"]
            state["_t0"] = None
            state["count"] += 1
            if pause > state["max_s"]:
                state["max_s"] = pause

    gc.callbacks.append(cb)
    return state, cb


def _heap_objects() -> int:
    """Tracked containers + the permanent generation (frozen) — the
    comparable total across the freeze/no-freeze postures."""
    import gc

    return len(gc.get_objects()) + gc.get_freeze_count()


def _maxrss_mb() -> float:
    import resource

    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def _mega_status_write_rate(store, rounds=3) -> dict:
    """Batched status-write throughput through the LIVE stack: rewrite
    every throttle's status via the store's batched UpdateStatus (the
    controllers' commit path — device-mirror echo, informer mirror, and
    controller handlers all subscribed). Median of ``rounds``."""
    import dataclasses

    thrs = store.list_throttles()
    rates = []
    for _ in range(rounds):
        batch = [t.with_status(dataclasses.replace(t.status)) for t in store.list_throttles()]
        t0 = time.perf_counter()
        store.update_throttle_statuses(batch)
        dt = time.perf_counter() - t0
        rates.append(len(batch) / dt)
    return {
        "throttles": len(thrs),
        "writes_per_sec_median": float(np.median(rates)),
        "writes_per_sec_runs": [round(r) for r in rates],
    }


def _mega_churn_window(store, plugin, P, groups, seconds=20.0, batch=256) -> dict:
    """Paced pod-churn window: request-size updates (the cfg5 shape)
    through ``apply_events`` batches, with GC pauses tracked. Returns
    applied events/s + max GC pause inside the window."""
    import random

    from kube_throttler_tpu.api.pod import make_pod
    from dataclasses import replace as _replace

    # replay build_served_stack's label assignment (same seed + draw
    # order) so churn is the cfg5 REQUEST-RESIZE shape — a wrong group
    # would turn every event into a label move (reservation migration +
    # index row re-match), a different and far heavier workload
    grp_rng = random.Random(0)
    grp_of = []
    for _ in range(P):
        grp_of.append(grp_rng.randrange(groups))
        grp_rng.randrange(1, 8)
    rng = random.Random(7)
    gc_stats, cb = _gc_pause_tracker()
    applied = 0
    t0 = time.perf_counter()
    try:
        while time.perf_counter() - t0 < seconds:
            ops = []
            for _ in range(batch):
                i = rng.randrange(P)
                pod = make_pod(
                    f"p{i}",
                    labels={"grp": f"g{grp_of[i]}"},
                    requests={"cpu": f"{rng.randrange(1, 8) * 100}m"},
                )
                pod = _replace(pod, spec=_replace(pod.spec, node_name="node-1"))
                pod.status.phase = "Running"
                ops.append(("upsert", "Pod", pod))
            store.apply_events(ops)
            applied += len(ops)
    finally:
        import gc

        gc.callbacks.remove(cb)
    dt = time.perf_counter() - t0
    return {
        "events_applied": applied,
        "events_per_sec": round(applied / dt, 1),
        "window_s": round(dt, 1),
        "gc_collections": gc_stats["count"],
        "gc_max_pause_ms": round(gc_stats["max_s"] * 1e3, 2),
    }


def _mega_equivalence_sweep(n_pods=1500, n_thr=120, seed=11) -> dict:
    """Seeded columnar ≡ frozen-dict ≡ batched ≡ sequential sweep: one op
    stream (creates / label moves / request updates / deletes / status
    recomputes) applied to (a) a columnar store batched, (b) a columnar
    store sequentially, (c) the frozen-dict reference store — asserting
    identical store dumps, identical published st_* planes, and identical
    pre_filter verdicts. The bench-level twin of
    tests/test_columnar_store.py's sweep, run at a larger shape."""
    import random

    from dataclasses import replace as _replace

    from kube_throttler_tpu.api.pod import Namespace, make_pod
    from kube_throttler_tpu.engine.store import Store
    from tools.harness import build_plugin, dump_store, make_throttle, recompute_status, verdicts

    def op_stream():
        rng = random.Random(seed)
        ops = []
        for i in range(n_thr):
            ops.append(("create", "Throttle", _replace(make_throttle(i % 40), name=f"t{i}")))
        for i in range(n_pods):
            pod = make_pod(
                f"p{i}", labels={"grp": f"g{rng.randrange(40)}"},
                requests={"cpu": f"{rng.randrange(1, 8) * 100}m"},
            )
            pod = _replace(pod, spec=_replace(pod.spec, node_name="node-1"))
            pod.status.phase = "Running"
            ops.append(("create", "Pod", pod))
        for _ in range(n_pods // 2):
            i = rng.randrange(n_pods)
            verb = rng.choice(["move", "resize", "delete", "revive"])
            if verb == "delete":
                ops.append(("delete", "Pod", f"default/p{i}"))
            else:
                pod = make_pod(
                    f"p{i}", labels={"grp": f"g{rng.randrange(40)}"},
                    requests={"cpu": f"{rng.randrange(1, 8) * 100}m"},
                )
                pod = _replace(pod, spec=_replace(pod.spec, node_name="node-1"))
                pod.status.phase = "Running"
                ops.append(("upsert", "Pod", pod))
        return ops

    # ONE op stream shared by all three runs: uids come from a process
    # counter, so regenerating per run would make the dumps differ by
    # uid alone (objects are immutable-by-convention — sharing them
    # across stores is safe, and the columnar absorb only canonicalizes
    # label/annotation dict identity, never content)
    shared_ops = op_stream()
    shared_ns = Namespace("default")  # one uid across all three runs

    def run(columnar: bool, batched: bool):
        store = Store(columnar=columnar)
        plugin = build_plugin(store)
        store.create_namespace(shared_ns)
        ops = shared_ops
        if batched:
            for s in range(0, len(ops), 64):
                store.apply_events(ops[s : s + 64])
        else:
            for op in ops:
                store.apply_events([op])
        # deterministic status writes (no wall-clock in the payload)
        for thr in store.list_throttles():
            store.update_throttle_status(recompute_status(store, thr))
        return (
            dump_store(store),
            plugin.device_manager.published_flags(),
            verdicts(plugin, store),
        )

    col_b = run(True, batched=True)
    col_s = run(True, batched=False)
    ref = run(False, batched=False)
    return {
        "pods": n_pods,
        "throttles": n_thr,
        "batched_eq_sequential": col_b == col_s,
        "columnar_eq_reference": col_s == ref,
        "dumps_equal": col_b[0] == col_s[0] == ref[0],
        "planes_equal": col_b[1] == col_s[1] == ref[1],
        "verdicts_equal": col_b[2] == col_s[2] == ref[2],
    }


def run_mega() -> None:
    """``python bench.py --mega``: the PR 11 acceptance artifact — the
    columnar-arena ladder up to 1M pods × 100k throttles on one host,
    recording RSS high-water, heap object count, and max GC pause
    alongside throughput; plus the 100k×10k status-write rung against the
    PR 2 frozen-dict baseline and the seeded equivalence sweep. Written
    to BENCH_PR11_<platform>_<stamp>.json."""
    import gc

    platform = "cpu"
    try:
        platform = jax.devices()[0].platform
    except Exception:
        pass
    out: dict = {
        "metric": (
            "columnar arena store ladder: 1M pods x 100k throttles on one "
            "host (RSS high-water, heap objects, max GC pause) + "
            "status-write throughput vs the PR 2 frozen-dict baseline"
        ),
        "platform": platform,
        "host_cores": os.cpu_count(),
        "columnar": True,
        "rungs": {},
    }

    log("[mega] seeded equivalence sweep (columnar vs frozen-dict reference)")
    eq = _mega_equivalence_sweep()
    out["equivalence"] = eq
    log(f"[mega] equivalence: {eq}")
    if not (eq["dumps_equal"] and eq["planes_equal"] and eq["verdicts_equal"]):
        log("[mega] EQUIVALENCE FAILED — aborting before the ladder")
        out["value"] = 0.0
        emit(out)
        return

    # PR 2 reference: the measured frozen-dict status-write ceiling
    # (docs/PERFORMANCE.md "What bounds each path")
    PR2_STATUS_WRITES_PER_SEC = 8000.0
    ladder = [
        ("100kx10k", 100_000, 10_000, 500),
        ("1Mx100k", 1_000_000, 100_000, 5000),
    ]
    for name, P, T, groups in ladder:
        log(f"[mega] ==== rung {name}: {P} pods x {T} throttles ====")
        gc_build, cb = _gc_pause_tracker()
        rss_before, heap_before = _maxrss_mb(), _heap_objects()
        t0 = time.perf_counter()
        store, plugin = build_served_stack(P, T, groups=groups, label=f"mega-{name}")
        build_s = time.perf_counter() - t0
        gc.callbacks.remove(cb)
        rung: dict = {
            "pods": P,
            "throttles": T,
            "build_seconds": round(build_s, 1),
            "rss_high_water_mb": round(_maxrss_mb(), 1),
            "rss_delta_mb": round(_maxrss_mb() - rss_before, 1),
            "heap_objects": _heap_objects(),
            "heap_objects_delta": _heap_objects() - heap_before,
            "heap_objects_per_pod": round((_heap_objects() - heap_before) / P, 4),
            "rss_bytes_per_pod": round((_maxrss_mb() - rss_before) * 1024 * 1024 / P),
            "build_gc_max_pause_ms": round(gc_build["max_s"] * 1e3, 2),
            "arena": store.pod_arena.stats() if store.pod_arena else None,
        }
        try:
            sw = _mega_status_write_rate(store)
            rung["status_writes"] = sw
            rung["status_writes_x_pr2"] = round(
                sw["writes_per_sec_median"] / PR2_STATUS_WRITES_PER_SEC, 2
            )
            log(
                f"[mega:{name}] status writes {sw['writes_per_sec_median']:,.0f}/s "
                f"({rung['status_writes_x_pr2']}x the PR2 8k/s baseline)"
            )
            churn = _mega_churn_window(store, plugin, P, groups)
            rung["churn"] = churn
            log(
                f"[mega:{name}] churn {churn['events_per_sec']:,.0f} ev/s, "
                f"max GC pause {churn['gc_max_pause_ms']}ms "
                f"({churn['gc_collections']} collections)"
            )
            r = host_percentiles(
                lambda: plugin.pre_filter(
                    make_probe_pod(groups)
                ),
                300,
                warmup=20,
                max_seconds=30.0,
            )
            rung["prefilter_p50_ms"] = round(r["p50"] * 1e3, 3)
            rung["prefilter_p99_ms"] = round(r["p99"] * 1e3, 3)
            log(
                f"[mega:{name}] pre_filter p50 {rung['prefilter_p50_ms']}ms / "
                f"p99 {rung['prefilter_p99_ms']}ms"
            )
        finally:
            plugin.stop()
            del store, plugin
            gc.collect()
        out["rungs"][name] = rung
        log(
            f"[mega:{name}] RSS {rung['rss_high_water_mb']}MB "
            f"({rung['rss_bytes_per_pod']}B/pod), heap {rung['heap_objects']:,} "
            f"objects ({rung['heap_objects_per_pod']}/pod), build {build_s:.0f}s"
        )

    big = out["rungs"].get("1Mx100k", {})
    out["value"] = float(big.get("churn", {}).get("events_per_sec", 0.0))
    out["unit"] = "events/s sustained at 1M pods x 100k throttles"
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    path = f"BENCH_PR11_{platform.upper()}_{stamp}.json"
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    log(f"mega ladder written to {path}")
    emit(out)


def make_probe_pod(groups: int):
    import random

    from kube_throttler_tpu.api.pod import make_pod

    i = random.randrange(groups)
    return make_pod(
        f"probe{i}", labels={"grp": f"g{i}"}, requests={"cpu": "300m"}
    )


def main():
    if "--mega" in sys.argv:
        # PR 11 acceptance artifact: the 1M x 100k columnar-arena ladder
        run_mega()
        return
    if "--ingest-sweep" in sys.argv:
        # PR 5 acceptance artifact: the full-scale batch-size sweep alone
        run_ingest_sweep()
        return
    if "--shard-sweep" in sys.argv:
        # PR 9 acceptance artifact: aggregate ingest/decisions/flip p99
        # across {1,2,4} shared-nothing worker processes
        run_shard_sweep()
        return
    if "--gang" in sys.argv:
        # gang-admission rung: bursty group arrivals + churn SLO check
        run_gang_bench()
        return
    if "--decision-sweep" in sys.argv:
        # PR 17 acceptance artifact: interned-verdict cache vs uncached
        # reference (cold/warm, 1/4 threads, epoch churn, oracle agreement)
        run_decision_sweep()
        return
    if "--route-sweep" in sys.argv:
        # PR 20 acceptance artifact: pickle-socketpair vs zero-copy shm
        # ring event transport (burst + sustained), plus the real-fleet
        # end-to-end sanity rung
        run_route_sweep()
        return
    quick = "--quick" in sys.argv
    rng = np.random.default_rng(0)
    start_watchdog()

    detail: dict = RESULT_STATE["detail"]
    errors: dict = RESULT_STATE["errors"]

    def safe(name, fn, *a, **k):
        """Fence one config: a failure records an error but never kills the run."""
        try:
            return fn(*a, **k)
        except Exception as e:
            log(f"[{name}] FAILED: {e.__class__.__name__}: {str(e)[:300]}")
            log(traceback.format_exc(limit=4))
            errors[name] = f"{e.__class__.__name__}: {str(e)[:200]}"
            return None

    if os.environ.get("KT_BENCH_CPU_FALLBACK") == "1":
        # Already re-exec'd onto CPU after an in-process init failure; probing
        # the down tunnel again would just burn the whole backoff budget.
        degraded = True
    else:
        # Leave at least ~8 minutes of deadline for the degraded CPU quick
        # run (measured ~6 min end-to-end) if the probe burns its budget.
        probe_budget = min(120.0 if quick else 600.0, max(60.0, time_left() - 480.0))
        degraded = not ensure_backend(max_wait=probe_budget)
        if degraded:
            log("backend never came up; degrading to CPU for this run")
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                pass
    RESULT_STATE["degraded"] = degraded
    devices = safe("init", init_devices_or_reexec)
    log(f"devices: {devices}")
    platform = devices[0].platform if devices else "none"
    RESULT_STATE["platform"] = platform
    # accelerator backends only (the helper declines on cpu/none): the
    # on-disk cache survives the probe subprocess and repeat runs
    if enable_persistent_compilation_cache(platform):
        log("persistent XLA compilation cache enabled")

    # degraded CPU fallback ALSO runs the quick shapes: the full 100k×10k
    # configs on a single host core take the best part of an hour — a
    # bounded 1/10-scale run with degraded=true beats a timed-out run with
    # no JSON line at all
    if degraded or platform == "cpu":
        if not quick:
            log("degraded/CPU platform: forcing --quick shapes (1/10 scale)")
        quick = True
    scale = 10 if quick else 1
    RESULT_STATE["scale"] = scale

    rtt = safe("rtt", measure_dispatch_rtt) if devices else None
    RESULT_STATE["rtt"] = rtt
    if rtt is not None:
        log(f"dispatch round-trip (environment tunnel overhead): {rtt*1e3:.1f}ms")
        detail["dispatch_rtt_ms"] = round(rtt * 1e3, 2)

    R = 8

    # config 1: the reference example scenario end-to-end (host path; device-free)
    cfg1 = safe("cfg1", bench_example_scenario, "cfg1:example")
    RESULT_STATE["cfg1"] = cfg1
    if cfg1:
        detail["cfg1_host_prefilter_p99_us"] = round(cfg1["p99"] * 1e6, 1)
    safe("host:index", bench_selector_index, "host:index", T=10_000 // scale)

    # The SERVED path (last section) is the headline; the bare-kernel
    # configs are supporting detail. When the backend probe has eaten the
    # deadline, skip straight to the headline instead of spending what's
    # left on kernels and letting the watchdog kill the part that matters.
    # Budgets (measured: quick-CPU kernels ~2min + served ~2min; full-TPU
    # kernels dominated by cfg4 compiles): thresholds must fit inside the
    # default 1800s deadline minus a fast probe, or full runs would always
    # skip the kernels.
    served_budget = 240.0 if scale == 10 else 900.0
    kernel_budget = 120.0 if scale == 10 else 420.0
    kernels_ok = time_left() > served_budget + kernel_budget
    if not kernels_ok:
        log(f"time budget low ({time_left():.0f}s left): skipping bare-kernel configs")
        errors["kernels"] = "skipped: low time budget after backend probe"

    single_stats = None
    if devices and kernels_ok:
        # config 2: 1k pods x 100 throttles, 4 active dims
        safe("cfg2", bench_batched, rng, 1000 // scale, 100, R, "cfg2:1kx100")

        # config 3: 10k x 1k
        safe("cfg3", bench_batched, rng, 10_000 // scale, 1000 // scale, R, "cfg3:10kx1k")

        # config 4: 100k x 10k with overrides (the headline)
        P, T = 100_000 // scale, 10_000 // scale
        safe("cfg4:overrides", bench_overrides, rng, T, 4, R, "cfg4:overrides")
        big = safe("cfg4:batched", bench_batched, rng, P, T, R, "cfg4:100kx10k")
        if platform in ("tpu", "axon"):  # the tunnel backend names itself either way
            safe("cfg4:pallas", bench_pallas_sweep, rng, P, T, R, "cfg4:100kx10k")
        else:
            log("[cfg4:pallas] skipped: pallas mosaic kernel needs the TPU backend")
        don = safe("cfg4:donation", bench_donation, rng, P, T, "cfg4:donation")
        if don:
            detail.update(don)
        if big is not None:
            state = big[0]
            safe("cfg4:single", bench_single_pod, rng, state, T, R, "cfg4:100kx10k")
            single_stats = safe(
                "cfg4:indexed", bench_single_pod_indexed, rng, state, T, R, "cfg4:100kx10k"
            )
            RESULT_STATE["single_stats"] = single_stats

        # config 5: streaming reconcile (bare device kernels)
        eps_scan = safe("cfg5:scan", bench_streaming, rng, T, R, "cfg5:streaming")
        eps_batch = safe("cfg5:batched", bench_streaming_batched, rng, T, R, "cfg5:streaming")
        if eps_batch:
            detail["cfg5_kernel_events_per_sec"] = round(eps_batch)
        elif eps_scan:
            detail["cfg5_kernel_events_per_sec"] = round(eps_scan)

    # ---- the SERVED paths (VERDICT r2 task 4): the full daemon stack at
    # the cfg4 scale — pre_filter end-to-end through check_pod (headline),
    # and cfg5 as store events through the controllers ----
    served_stats = None
    if devices and time_left() < served_budget:
        log(f"time budget exhausted ({time_left():.0f}s left): skipping served path")
        errors["served"] = "skipped: low time budget"
    elif devices:
        stack = safe(
            "served:setup", build_served_stack, 100_000 // scale, 10_000 // scale
        )
        if stack:
            store_s, plugin_s = stack
            # cfg5_*/served_* numbers are NOT comparable across scales: at
            # the full config every churn event dirties ~40 throttle keys
            # (20 per group per kind) vs 4 at the quick scale
            detail["served_scale"] = [100_000 // scale, 10_000 // scale]
            r = safe("served:prefilter", bench_served_prefilter, plugin_s, "served")
            if r:
                served_stats, rate1, rate4, rate4_co = r
                detail["served_decisions_per_sec_4t_coalesced"] = round(rate4_co)
                RESULT_STATE["served_stats"] = served_stats
                detail["served_p50_ms"] = round(served_stats["p50"] * 1e3, 4)
                detail["served_decisions_per_sec_1t"] = round(rate1)
                detail["served_decisions_per_sec_4t"] = round(rate4)
                detail["served_decisions_per_sec_median"] = round(
                    served_stats["decisions_per_sec_median"]
                )
                detail["served_decisions_cv"] = round(
                    served_stats["decisions_cv"], 4
                )
                detail["served_thread_scaling"] = round(rate4 / max(rate1, 1e-9), 2)
            cx = safe("served:coalesce-x", bench_coalesce_crossover, plugin_s, "served")
            if cx:
                detail["coalesce_emulated_dispatch_ms"] = cx["dispatch_ms"]
                detail["coalesce_direct_4t_per_sec"] = round(cx["direct_per_sec"])
                detail["coalesce_coalesced_4t_per_sec"] = round(
                    cx["coalesced_per_sec"]
                )
                detail["coalesce_crossover_ratio"] = round(cx["ratio"], 2)
            b = safe("served:batch", bench_served_batch, plugin_s, "served")
            if b:
                detail["served_batch_pods_per_sec"] = round(b["pods_per_sec"])
                detail["served_batch_ms"] = round(b["secs"] * 1e3, 2)
            tick = safe("served:tick", bench_served_tick, plugin_s, "served")
            if tick:
                detail["served_tick_ms"] = round(tick * 1e3)
            s = safe(
                "served:streaming",
                bench_served_streaming,
                store_s,
                plugin_s,
                "served",
                # the max-rate tail needs a longer window than the paced
                # run: p99 over a 5s window is ~10 drain cycles and lands
                # anywhere within this 1-CPU host's ~2x scheduling noise;
                # 10s halves the spread
                duration=10.0,
            )
            if s:
                detail["cfg5_served_events_per_sec"] = round(s["events_per_sec"])
                detail["cfg5_maxrate_lag_p99_ms"] = round(s["lag_p99_ms"], 2)
            # lag at a SUSTAINED 2.5k ev/s (VERDICT r3 task 2's "≥2k ev/s"
            # framing): max rate is open-loop saturation where lag is
            # definitionally backlog-bound; this measures the tail with the
            # pipeline loaded but not drowning
            s25 = safe(
                "served:streaming-2500",
                bench_served_streaming,
                store_s,
                plugin_s,
                "served",
                pace_hz=2500.0,
                duration=10.0,
            )
            if s25:
                detail["cfg5_2500hz_events_per_sec"] = round(s25["events_per_sec"])
                detail["cfg5_2500hz_lag_p99_ms"] = round(s25["lag_p99_ms"], 2)
            # the REMOTE wire loop (watch → reflector → reconcile → HTTP
            # status PUT), small fixed scale — wire overhead dominates and
            # the number answers "does remote mode keep up", not "how big"
            rw = safe("served:remote-wire", bench_remote_pipeline, "served")
            if rw:
                detail["cfg5_remote_events_per_sec"] = round(rw["events_per_sec"])
                detail["cfg5_remote_lag_p50_ms"] = round(rw["lag_p50_ms"], 2)
                detail["cfg5_remote_lag_p99_ms"] = round(rw["lag_p99_ms"], 2)
                detail["cfg5_remote_status_puts"] = rw["status_writes"]
                detail["cfg5_remote_flip_lag_p50_ms"] = round(rw["flip_lag_p50_ms"], 2)
                detail["cfg5_remote_flip_lag_p99_ms"] = round(rw["flip_lag_p99_ms"], 2)
                detail["cfg5_remote_flip_samples"] = rw["flip_samples"]
                detail["cfg5_remote_flip_crossings"] = rw["flip_crossings"]
                detail["cfg5_remote_wire_put_rtt_ms"] = rw["wire_put_rtt_ms"]
            # steady-state status-write lag at the BASELINE 1k/s target load
            s2 = safe(
                "served:streaming-paced",
                bench_served_streaming,
                store_s,
                plugin_s,
                "served",
                pace_hz=1000.0,
            )
            if s2:
                detail["cfg5_paced_events_per_sec"] = round(s2["events_per_sec"])
                detail["cfg5_paced_fired_per_sec"] = round(s2["fired_events_per_sec"])
                detail["cfg5_status_lag_p50_ms"] = round(s2["lag_p50_ms"], 2)
                detail["cfg5_status_lag_p99_ms"] = round(s2["lag_p99_ms"], 2)
                detail["cfg5_flip_lag_p50_ms"] = round(s2["flip_lag_p50_ms"], 2)
                detail["cfg5_flip_lag_p99_ms"] = round(s2["flip_lag_p99_ms"], 2)
                detail["cfg5_flip_samples"] = s2["flip_samples"]
                detail["cfg5_flip_crossings"] = s2["flip_crossings"]
                detail["cfg5_lag_mode"] = "paced-1k"
            elif s:  # paced window failed: keep the max-rate lag measurement
                detail["cfg5_status_lag_p50_ms"] = round(s["lag_p50_ms"], 2)
                detail["cfg5_status_lag_p99_ms"] = round(s["lag_p99_ms"], 2)
                detail["cfg5_lag_mode"] = "max-rate"
            safe("served:stop", plugin_s.stop)

        if scale != 1 and time_left() > 240.0:
            # FULL-SCALE entries even on the degraded/quick path (VERDICT r4
            # task 2): the 100k×10k daemon is viable on one CPU core since
            # the host-side sparse rebase (setup ~55s, was ~363s) — run a
            # bounded full-scale setup + batch triage + paced cfg5 window
            # and label the entries explicitly. Each number is honest about
            # its window; nothing here overwrites the quick-scale fields.
            def fullscale():
                t0 = time.perf_counter()
                store_f, plugin_f = build_served_stack(
                    100_000, 10_000, label="served-full"
                )
                detail["fullscale_setup_s"] = round(time.perf_counter() - t0, 1)
                try:
                    # the BASELINE north-star metric AT ITS OWN SCALE:
                    # per-decision PreFilter percentiles against the live
                    # 100k×10k daemon state (the gather path is O(K·R), so
                    # this also demonstrates decision cost ~independent of
                    # cluster size). Becomes the headline when present.
                    # n sized for headline stability: 3 interleaved bands of
                    # 800 calls ≈ 0.9s each at full scale — short bands sat
                    # inside single scheduler slices and read CV ~0.26
                    fs_stats, fs_r1, fs_r4, fs_r4co = bench_served_prefilter(
                        plugin_f, "served-full", n=2400
                    )
                    detail["fullscale_p50_ms"] = round(fs_stats["p50"] * 1e3, 4)
                    detail["fullscale_p99_ms"] = round(fs_stats["p99"] * 1e3, 4)
                    detail["fullscale_decisions_per_sec"] = round(
                        fs_stats["decisions_per_sec_median"]
                    )
                    detail["fullscale_decisions_cv"] = round(
                        fs_stats["decisions_cv"], 4
                    )
                    RESULT_STATE["served_stats_full"] = fs_stats
                    b = bench_served_batch(plugin_f, "served-full", iters=3)
                    detail["fullscale_batch_pods_per_sec"] = round(
                        b["pods_per_sec"]
                    )
                    try:
                        tick_f = bench_served_tick(plugin_f, "served-full")
                        detail["fullscale_tick_ms"] = round(tick_f * 1e3)
                    except Exception as e:  # noqa: BLE001 — isolate like
                        # safe('served:tick'): a tick failure must not drop
                        # the downstream full-scale cfg5 measurements
                        errors["served-full:tick"] = f"{e.__class__.__name__}: {e}"
                    plugin_f.start()
                    # capacity window FIRST (max rate, longer window so the
                    # fixed drain tail doesn't dilute the sustained rate):
                    # the ≥1k events/s criterion reads this one
                    sm = bench_served_streaming(
                        store_f, plugin_f, "served-full", duration=12.0,
                    )
                    detail["fullscale_cfg5_maxrate_events_per_sec"] = round(
                        sm["events_per_sec"]
                    )
                    detail["fullscale_cfg5_maxrate_fired_per_sec"] = round(
                        sm["fired_events_per_sec"]
                    )
                    detail["fullscale_cfg5_maxrate_lag_p99_ms"] = round(
                        sm["lag_p99_ms"], 1
                    )
                    # then the steady-state window at the nominal 1k/s load
                    # — the lag and flip-lag numbers come from here
                    sf = bench_served_streaming(
                        store_f, plugin_f, "served-full",
                        duration=8.0, pace_hz=1000.0,
                    )
                    detail["fullscale_cfg5_events_per_sec"] = round(
                        sf["events_per_sec"]
                    )
                    detail["fullscale_cfg5_fired_per_sec"] = round(
                        sf["fired_events_per_sec"]
                    )
                    detail["fullscale_cfg5_lag_p50_ms"] = round(
                        sf["lag_p50_ms"], 1
                    )
                    detail["fullscale_cfg5_lag_p99_ms"] = round(
                        sf["lag_p99_ms"], 1
                    )
                    detail["fullscale_cfg5_flip_lag_p50_ms"] = round(
                        sf["flip_lag_p50_ms"], 1
                    )
                    detail["fullscale_cfg5_flip_lag_p99_ms"] = round(
                        sf["flip_lag_p99_ms"], 1
                    )
                    detail["fullscale_cfg5_flip_samples"] = sf["flip_samples"]
                    detail["fullscale_cfg5_flip_crossings"] = sf["flip_crossings"]
                    detail["fullscale_scale"] = [100_000, 10_000]
                    if time_left() > 120.0:
                        # micro-batched ingest rungs (PR 5): burst-drain
                        # capacity + the paced flip-SLO window (the full
                        # 1/adaptive/fixed sweep lives in --ingest-sweep)
                        si = bench_ingest_burst(
                            store_f, plugin_f, "served-full:ingest", n=30_000
                        )
                        detail["fullscale_ingest_capacity_events_per_sec"] = si[
                            "events_per_sec_sustained"
                        ]
                        ss = bench_served_streaming(
                            store_f, plugin_f, "served-full:ingest-slo",
                            duration=10.0, pace_hz=3200.0,
                            ingest_batch="adaptive",
                        )
                        detail["fullscale_ingest_slo_events_per_sec"] = round(
                            ss["events_per_sec"]
                        )
                        detail["fullscale_ingest_slo_flip_p99_ms"] = round(
                            ss["flip_lag_p99_ms"], 1
                        )
                finally:
                    try:
                        plugin_f.stop()
                    except Exception:
                        pass

            safe("served:fullscale", fullscale)

    emit(build_result())


def build_result() -> dict:
    """Assemble the one JSON line from whatever RESULT_STATE holds so far.

    Called by main() on the normal path and by the watchdog thread on the
    deadline path — every input is read with a safe default so a partial
    run still produces an honest (degraded/fallback) record.
    """
    def _snap(d: dict) -> dict:
        # the watchdog thread snapshots while main may be inserting; a dict
        # resize mid-copy raises RuntimeError — retry rather than lose the
        # collected measurements to the bare fallback
        for _ in range(8):
            try:
                return dict(d)
            except RuntimeError:
                time.sleep(0.01)
        return {}

    detail = _snap(RESULT_STATE["detail"])
    errors = _snap(RESULT_STATE["errors"])
    served_stats = RESULT_STATE.get("served_stats")
    single_stats = RESULT_STATE.get("single_stats")
    cfg1 = RESULT_STATE.get("cfg1")
    platform = RESULT_STATE.get("platform", "none")
    degraded = RESULT_STATE.get("degraded", True)
    scale = RESULT_STATE.get("scale", 10)

    target_ms = 1.0  # BASELINE north star: <1ms p99 on one v5e-1
    # when the full-scale (100k×10k) per-decision measurement ran, IT is
    # the headline — the north-star metric at the north-star scale; the
    # quick-scale percentiles stay in detail (served_p99_raw_ms etc.)
    served_stats_full = RESULT_STATE.get("served_stats_full")
    headline_scale = scale
    if served_stats_full is not None:
        served_stats = served_stats_full
        headline_scale = 1
    if served_stats is not None:
        # THE headline: end-to-end PreFilter through the real daemon stack,
        # reported RAW. (A former revision subtracted one tunnel RTT on the
        # remote-chip platform, from when every decision made one blocking
        # device read; since the backend-routed host classifier, the served
        # per-decision path makes ZERO blocking device reads on
        # accelerators, so there is no network component to net out —
        # subtracting produced a clamped fiction. dispatch_rtt_ms stays in
        # the JSON as environment context for the kernel slope timings.)
        raw_p99_ms = served_stats["p99"] * 1e3
        value_ms = max(raw_p99_ms, 1e-3)
        detail["served_p99_raw_ms"] = round(raw_p99_ms, 4)
        if served_stats_full is not None:
            # headline is the full-scale measurement; its p50 pairs with it
            # (the quick-scale p50 stays under served_p50_ms)
            detail["served_p50_raw_ms"] = round(served_stats_full["p50"] * 1e3, 4)
        else:
            detail["served_p50_raw_ms"] = detail.pop("served_p50_ms", None)
        if single_stats is not None:
            detail["kernel_p99_ms"] = round(
                max(float(single_stats["p99"]) * 1e3, 1e-4), 4
            )
            detail["single_cv"] = round(single_stats["cv"], 4)
        state_label = (
            f"{100_000 // headline_scale // 1000}k-pod/"
            f"{10_000 // headline_scale // 1000}k-throttle"
        )
        metric = (
            "SERVED PreFilter decision p99 latency: plugin.pre_filter end-to-end "
            f"(device-indexed check) vs live {state_label} daemon state, "
            f"1 {platform} chip"
        )
        comparable = True
    elif single_stats is not None:
        value_ms = max(float(single_stats["p99"]) * 1e3, 1e-4)  # slope noise floor
        detail["single_mean_ms"] = round(max(single_stats["mean"] * 1e3, 1e-4), 4)
        detail["single_cv"] = round(single_stats["cv"], 4)
        metric = (
            f"PreFilter decision latency, single pod vs "
            f"{100_000 // scale // 1000}k-pod/{10_000 // scale // 1000}k-throttle state "
            "(p99 over slope estimates, bare kernel — served path unavailable, "
            f"see errors; 1 {platform} chip)"
        )
        comparable = True
    elif cfg1 is not None:
        # device headline config unavailable (backend down, or cfg4 itself
        # failed — see `errors`): fall back to the honest host-path p99 so the
        # round still records a real measurement rather than nothing.
        value_ms = cfg1["p99"] * 1e3
        metric = "PreFilter decision p99 latency, host-oracle path (device headline config unavailable)"
        comparable = False
    else:
        value_ms, metric = -1.0, "bench failed; see errors"
        comparable = False

    # vs_baseline compares against the device-path north star; a host-only
    # fallback number is not comparable and must not record a fake win.
    comparable = comparable and value_ms > 0
    out = {
        "metric": metric,
        "value": round(value_ms, 4),
        "unit": "ms",
        "vs_baseline": round(target_ms / value_ms, 3) if comparable else 0.0,
        "p99_ms": round(value_ms, 4),
        "platform": platform,
        "degraded": degraded,
        # context for the thread-scaling numbers: all host-side work (GIL,
        # controllers, the bench's own load generators) shares these cores
        "host_cpus": os.cpu_count(),
        **detail,
    }
    if errors:
        out["errors"] = errors  # already a point-in-time snapshot (_snap)
    return out


if __name__ == "__main__":
    try:
        main()
    except BaseException as e:  # absolute last resort: never exit without the JSON line
        if isinstance(e, SystemExit) and not e.code:
            raise
        log(traceback.format_exc())
        RESULT_STATE["errors"]["fatal"] = f"{e.__class__.__name__}: {str(e)[:300]}"
        try:
            out = build_result()
        except BaseException:
            out = {
                "metric": "bench crashed",
                "value": -1.0,
                "unit": "ms",
                "vs_baseline": 0.0,
                "error": f"{e.__class__.__name__}: {str(e)[:300]}",
            }
        emit(out)
        # rc=0 only when a usable partial MEASUREMENT made it out (value>0);
        # a crash that measured nothing must stay distinguishable by rc.
        usable = out.get("value", -1.0) > 0
        sys.exit(130 if isinstance(e, KeyboardInterrupt) else (0 if usable else 1))
