"""The reference README walkthrough (README.md:202-375) against the full
kube_throttler_tpu stack: in-memory apiserver → watch events → controllers →
device-kernel-served PreFilter.

Run: python examples/walkthrough.py
"""

import sys
from dataclasses import replace

sys.path.insert(0, ".")

from kube_throttler_tpu.utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()

from kube_throttler_tpu.api import (
    LabelSelector,
    Namespace,
    ResourceAmount,
    Throttle,
    ThrottleSelector,
    ThrottleSelectorTerm,
    ThrottleSpec,
)
from kube_throttler_tpu.api.pod import make_pod
from kube_throttler_tpu.engine.store import Store
from kube_throttler_tpu.plugin import KubeThrottler, RecordingEventRecorder, decode_plugin_args


def main():
    store = Store()
    store.create_namespace(Namespace("default"))
    recorder = RecordingEventRecorder()
    plugin = KubeThrottler(
        decode_plugin_args({"name": "kube-throttler", "targetSchedulerName": "my-scheduler"}),
        store,
        event_recorder=recorder,
    )

    store.create_throttle(
        Throttle(
            name="t1",
            spec=ThrottleSpec(
                throttler_name="kube-throttler",
                threshold=ResourceAmount.of(pod=5, requests={"cpu": "200m", "memory": "1Gi"}),
                selector=ThrottleSelector(
                    selector_terms=(
                        ThrottleSelectorTerm(LabelSelector(match_labels={"throttle": "t1"})),
                    )
                ),
            ),
        )
    )
    plugin.run_pending_once()

    def attempt(pod):
        store.create_pod(pod)
        plugin.run_pending_once()
        status = plugin.pre_filter(pod)
        if status.is_success():
            plugin.reserve(pod)
            bound = replace(pod, spec=replace(pod.spec, node_name="node-1"))
            bound.status.phase = "Running"
            store.update_pod(bound)
            plugin.run_pending_once()
            print(f"  {pod.name}: SCHEDULED")
        else:
            print(f"  {pod.name}: Pending — {status.message()}")

    print("create pod1 (cpu=200m):")
    attempt(make_pod("pod1", labels={"throttle": "t1"}, requests={"cpu": "200m"}))
    thr = store.get_throttle("default", "t1")
    print(f"  t1 status: used={thr.status.used.to_dict()} throttled={thr.status.throttled.to_dict()}")

    print("create pod2 (cpu=300m):")
    attempt(make_pod("pod2", labels={"throttle": "t1"}, requests={"cpu": "300m"}))

    print("create pod1m (memory=512Mi):")
    attempt(make_pod("pod1m", labels={"throttle": "t1"}, requests={"memory": "512Mi"}))

    print("edit t1 threshold to cpu=700m:")
    thr = store.get_throttle("default", "t1")
    store.update_throttle(
        replace(thr, spec=replace(thr.spec, threshold=ResourceAmount.of(pod=5, requests={"cpu": "700m", "memory": "1Gi"})))
    )
    plugin.run_pending_once()
    print("retry pod2:")
    attempt_pod2 = store.get_pod("default", "pod2")
    status = plugin.pre_filter(attempt_pod2)
    if status.is_success():
        plugin.reserve(attempt_pod2)
        bound = replace(attempt_pod2, spec=replace(attempt_pod2.spec, node_name="node-1"))
        bound.status.phase = "Running"
        store.update_pod(bound)
        plugin.run_pending_once()
        print("  pod2: SCHEDULED")

    print("create pod3 (cpu=300m, used=500m of 700m):")
    attempt(make_pod("pod3", labels={"throttle": "t1"}, requests={"cpu": "300m"}))
    for e in recorder.events:
        print(f"  event: {e.pod_key} {e.event_type}/{e.reason}")


if __name__ == "__main__":
    main()
