"""Remote-mode demo: the daemon throttling a (simulated) external cluster.

Spins up the in-process wire-protocol apiserver (client/mockserver.py),
launches the REAL daemon binary against a generated kubeconfig, drives pod
churn on the "cluster", and shows:

- reflectors syncing the daemon's cache over real HTTP list+watch,
- the reconcile loop writing ``status.used`` back to the status
  subresource,
- admission decisions served over the daemon's /v1/prefilter,
- Warning events landing on the cluster as v1 Events.

Run: python examples/remote_mode.py
"""

import json
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from kube_throttler_tpu.api.pod import Namespace, make_pod  # noqa: E402
from kube_throttler_tpu.api.serialization import object_from_dict  # noqa: E402
from kube_throttler_tpu.client.mockserver import MockApiServer  # noqa: E402

THROTTLE = {
    "kind": "Throttle",
    "metadata": {"name": "t1", "namespace": "default"},
    "spec": {
        "throttlerName": "kube-throttler",
        "threshold": {"resourceRequests": {"cpu": "1"}},
        "selector": {"selectorTerms": [{"podSelector": {"matchLabels": {"grp": "a"}}}]},
    },
}


def main() -> int:
    server = MockApiServer()
    server.store.create_namespace(Namespace("default"))
    server.store.create_throttle(object_from_dict(THROTTLE))
    server.start()
    print(f"cluster (wire-protocol apiserver) on {server.url}")

    kubeconfig = Path("/tmp/kt-remote-demo-kubeconfig.yaml")
    kubeconfig.write_text(
        f"clusters:\n- name: demo\n  cluster: {{server: \"{server.url}\"}}\n"
        "contexts:\n- name: demo\n  context: {cluster: demo}\ncurrent-context: demo\n"
    )

    daemon = subprocess.Popen(
        [
            sys.executable, "-m", "kube_throttler_tpu.cli", "serve",
            "--name", "kube-throttler", "--target-scheduler-name", "my-scheduler",
            "--kubeconfig", str(kubeconfig), "--port", "0", "--no-device",
        ],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    port = None
    try:
        for line in daemon.stdout:
            print(f"daemon: {line.rstrip()}")
            if "serving on" in line:
                port = int(line.split("serving on ")[1].split()[0].split(":")[1])
                break
        assert port, "daemon did not start"

        # keep draining the (merged) pipe in the background, or the daemon
        # blocks on a log write once the OS pipe buffer fills
        import threading

        threading.Thread(
            target=lambda: [None for _ in daemon.stdout], daemon=True
        ).start()

        # a Running 800m pod lands on the cluster → reconcile → status.used
        pod = make_pod(
            "p1",
            labels={"grp": "a"},
            requests={"cpu": "800m"},
            node_name="node-1",
            phase="Running",
        )
        server.store.create_pod(pod)
        deadline = time.time() + 20
        while time.time() < deadline:
            t1 = server.store.get_throttle("default", "t1")
            if t1.status.used.resource_counts == 1:
                break
            time.sleep(0.05)
        print(f"cluster sees status.used = {t1.status.used.to_dict()}")

        def prefilter(name, cpu):
            body = {
                "kind": "Pod",
                "metadata": {"name": name, "namespace": "default", "labels": {"grp": "a"}},
                "spec": {
                    "schedulerName": "my-scheduler",
                    "containers": [{"resources": {"requests": {"cpu": cpu}}}],
                },
            }
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/prefilter",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            out = json.load(urllib.request.urlopen(req, timeout=10))
            print(f"prefilter {name} ({cpu}): {out['code']} {out['reasons']}")

        prefilter("small", "100m")   # fits under 1 CPU
        prefilter("big", "300m")     # 800m used + 300m > 1 → insufficient
        prefilter("huge", "5")       # alone exceeds → Warning event emitted
        time.sleep(1)
        events = server.events_in("default")
        for ev in events:
            print(f"cluster event: {ev['type']} {ev['reason']} on {ev['involvedObject']['name']}")
        return 0
    finally:
        daemon.terminate()
        try:
            daemon.wait(timeout=10)
        except subprocess.TimeoutExpired:
            daemon.kill()
            daemon.wait(timeout=10)
        server.stop()


if __name__ == "__main__":
    sys.exit(main())
