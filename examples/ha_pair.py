#!/usr/bin/env python
"""Two-replica active/standby failover demo (docs/robustness.md "High
availability & fencing").

Launches a LEADER daemon (``--ha-role leader``) and a warm STANDBY
(``--ha-role standby --replicate-from <leader>``) sharing a flock lease,
creates a throttle and pods on the leader, shows the standby replicating
(503 ``standby`` on /readyz while it streams the journal tail), then
SIGKILLs the leader and watches the standby promote itself — epoch
bumped, replicated objects served, admission answering — within a couple
of seconds.

Run:  python examples/ha_pair.py
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def launch(role: str, workdir: str, lock: str, port: int, extra):
    datadir = os.path.join(workdir, role)
    os.makedirs(datadir, exist_ok=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen(
        [
            sys.executable, "-m", "kube_throttler_tpu.cli", "serve",
            "--name", "kube-throttler", "--target-scheduler-name", "my-scheduler",
            "--no-device", "--data-dir", datadir, "--port", str(port),
            "--lock-file", lock, "--ha-role", role,
        ] + extra,
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )


def wait_for(proc, needle: str, timeout_s: float = 60.0) -> None:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                raise RuntimeError(f"daemon exited rc={proc.returncode}")
            time.sleep(0.05)
            continue
        print(f"    | {line.rstrip()}")
        if needle in line:
            return
    raise RuntimeError(f"timed out waiting for {needle!r}")


def post(port: int, path: str, doc: dict) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"},
    )
    return json.loads(urllib.request.urlopen(req).read())


def get(port: int, path: str):
    return json.loads(urllib.request.urlopen(f"http://127.0.0.1:{port}{path}").read())


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="ha-pair-") as workdir:
        lock = os.path.join(workdir, "lease.lock")
        leader = standby = None
        try:
            print("[1] starting the LEADER (epoch 1, replication endpoints on)")
            leader = launch("leader", workdir, lock, 10259, [])
            wait_for(leader, "serving on")

            print("[2] creating a throttle + pods through the leader")
            post(10259, "/v1/objects", {
                "kind": "Throttle",
                "metadata": {"name": "demo", "namespace": "default"},
                "spec": {
                    "throttlerName": "kube-throttler",
                    "threshold": {"resourceCounts": {"pod": 2}},
                    "selector": {"selectorTerms": [
                        {"podSelector": {"matchLabels": {"app": "demo"}}}
                    ]},
                },
            })
            for i in range(3):
                post(10259, "/v1/objects", {
                    "kind": "Pod",
                    "metadata": {"name": f"demo-{i}", "namespace": "default",
                                 "labels": {"app": "demo"}},
                    "spec": {"schedulerName": "my-scheduler",
                             "containers": [{"name": "c", "resources": {
                                 "requests": {"cpu": "100m"}}}]},
                })

            print("[3] starting the WARM STANDBY (bootstraps + streams the tail)")
            standby = launch(
                "standby", workdir, lock, 10260,
                ["--replicate-from", "http://127.0.0.1:10259"],
            )
            wait_for(standby, "standing by")
            try:
                get(10260, "/readyz")
            except urllib.error.HTTPError as e:
                body = json.loads(e.read())
                print(f"    standby /readyz: {e.code} state={body['state']} "
                      f"(lag {body['components']['ha'].get('lagBytes')} bytes)")

            print("[4] SIGKILL the leader — no goodbye, no snapshot, no release")
            t0 = time.time()
            leader.send_signal(signal.SIGKILL)
            leader.wait()

            print("[5] the standby takes the lease, fast-forwards, and serves")
            wait_for(standby, "serving on")
            ready = get(10260, "/readyz")
            throttles = get(10260, "/v1/throttles")
            verdict = post(10260, "/v1/prefilter", {"podKey": "default/demo-0"})
            print(f"\n    failover: {time.time() - t0:.2f}s after the kill")
            print(f"    role={ready['role']} epoch={ready['epoch']} "
                  f"(the dead leader's term was 1)")
            print(f"    replicated throttles: "
                  f"{[t['metadata']['name'] for t in throttles]}")
            print(f"    admission verdict for default/demo-0: {verdict}")
            return 0
        finally:
            for p in (leader, standby):
                if p is not None and p.poll() is None:
                    p.terminate()
                    try:
                        p.wait(timeout=15)
                    except subprocess.TimeoutExpired:
                        p.kill()


if __name__ == "__main__":
    sys.exit(main())
